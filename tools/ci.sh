#!/usr/bin/env bash
# One-command CI for this repo (toolchain-less CPU container):
#
#   1. tier-1 forced-CPU test suite (the ROADMAP gate, verbatim)
#   1b. the same tier-1 suite with PPLS_SCOUT=1 — every trapezoid
#       walker run is forced through the round-12 f32 scouting kernel
#       (mirroring the PPLS_DEBUG_NANS opt-in lane), so the scout path
#       cannot rot between TPU-attached rounds
#   1c. the same tier-1 suite with PPLS_CHAOS=1 — every checkpoint
#       write immediately re-opens and checksum-verifies itself
#       (runtime/checkpoint.py's verify-on-write lane) and the serve
#       CLI always routes through the Supervisor, so the round-14
#       integrity/recovery machinery re-proves itself suite-wide
#   2. `pip install -e .` smoke + `ppls-tpu --help` console script
#   3. artifact schema check (BENCH_r*/MULTICHIP_r* round JSONs)
#   4. graftlint static analysis (AST tier GL01-GL06 + GL11 vs the
#      committed baseline)
#   4b. graftlint DEEP tier (round 17): `--deep` traces the real
#       jitted engine programs (walker cycle, stream phase, both dd
#       modes, bag, wavefront) on CPU — interpret mode, virtual
#       8-mesh for dd — and walks the captured jaxprs: GL07
#       collective census vs the crounds model, GL08 f32->f64
#       origin audit, GL09 host-interop census, GL10 jaxpr-hash
#       stability across operand values. One trace pass serves all
#       four rules (wall budget enforced below); the machine-readable
#       --format json ledger is schema-gated by check_artifacts
#       --graftlint
#   5. serve telemetry smoke: a short seeded synthetic Poisson load
#      through `ppls-tpu serve --events`, then the event-log schema
#      check (the round-10 timeline artifact must stay valid end-to-end)
#   5b. seeded CHAOS drain (round 14): `ppls-tpu serve` under the
#       committed fault plans — stage 1 (tools/chaos_plan.json, dd
#       stream on the virtual 8-mesh): NaN poison + injected hang +
#       chip loss, the supervisor must quarantine / watchdog-resume /
#       resize-resume onto 7 chips and drain green; stage 2
#       (tools/chaos_plan_ckpt.json, single chip): snapshot corruption
#       + phase-boundary crash, the resume must detect the corrupt
#       file and self-heal by starting fresh. Both timelines validate
#       through tools/check_artifacts.py --events (crashed prefixes
#       allowed), and the summaries' recovery records are asserted.
#   5c. CHAOS UNDER LOAD (round 16): a seeded Poisson overload beyond
#       capacity (12 requests at ~8/phase into a 4-slot dd stream with
#       a 5-deep bounded queue, three tenants across three priority
#       classes) with chip-loss + NaN poison injected, through
#       `serve --supervise`. The summary must show the shed/quarantine
#       /per-class-SLO story (completed + shed == offered, quarantine
#       == 1, resize-resume recovery) and the stdout ledger + events
#       timeline validate through tools/check_artifacts.py --serve /
#       --events (rid-deduped accounting invariants).
#   5d. MULTICHIP process-count sweep (round 18): the real local CPU
#       cluster (worker subprocesses behind one coordinator) serves
#       the identical dyadic workload at {1, 2, 4} processes under a
#       wall budget; per-request areas must be BIT-IDENTICAL across
#       the sweep and every ledger validates via check_artifacts
#       --serve. Round 19 adds the FEDERATED METRICS leg: a
#       --processes 2 run with --metrics-port 0 scraped LIVE, the
#       final post-summary sample (PPLS_SERVE_METRICS_HOLD window)
#       asserting the reconciliation invariant (coordinator-merged
#       retired == sum over worker processes + spillover ==
#       summary.completed). The chaos timelines of 5b/5c additionally
#       pass the round-19 rid-linkage check (--rid-linkage) and 5c's
#       timeline must decompose exactly through
#       tools/analyze_request.py --check
#   5e. HETEROGENEOUS DISPATCH under chaos (round 21): a mixed-shape
#       workload (two eps bands, a simpson request, a theta-block-2
#       batch — 4 distinct engine keys — plus a malformed line)
#       through `serve --dispatch --supervise` with the committed
#       crash plan (tools/chaos_plan_dispatch.json). The summary must
#       hold the pool invariants: recompiles == 0 across mixed shapes
#       AND across the kill-and-resume, >= 3 engine keys live,
#       per-engine completions reconciling, the malformed line
#       rejected per-line; ledger + timeline validate via
#       check_artifacts --serve / --events --rid-linkage
#   6. bench observatory: tools/bench_history.py --check over the
#      committed round artifacts + the quick-proxy regression gate
#      (device-counted proxies vs tools/bench_quick_ref.json; round
#      18 adds the multihost block — redeal wall, spillover-engaged
#      fraction, zero-lost-acks + bit-identity invariants; round 21
#      adds the dispatch block — zero recompiles on the mixed-shape
#      pool, per-engine reconciliation, work-conserving speedup floor
#      vs the serialized one-engine-at-a-time baseline)
#   6c. bench.py multihost record schema check (kill-one-host under
#       overload on the 2-process cluster; exit nonzero when
#       spillover failed to engage or areas diverged)
#   7. C hygiene smoke: csrc compiles under -Wall -Wextra -Werror
#      (skipped with a visible notice when no compiler is present)
#
# Usage: bash tools/ci.sh            # from anywhere inside the repo
#        PPLS_CI_SKIP_INSTALL=1 bash tools/ci.sh   # tests + schema only
set -u -o pipefail

ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$ROOT"
FAILURES=0

step() { echo; echo "=== ci: $* ==="; }

# --- 1. tier-1 suite (keep in sync with ROADMAP.md "Tier-1 verify") ---
step "tier-1 forced-CPU test suite"
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "ci: tier-1 suite FAILED (rc=$rc)"
    FAILURES=$((FAILURES + 1))
fi

# --- 1b. tier-1 again with scouting FORCED ON (PPLS_SCOUT=1) ---
# The f32 scout kernel only runs when a caller opts in; without this
# lane a regression in the scout step would sit invisible until the
# next TPU round. PPLS_SCOUT=1 flips every default-mode trapezoid
# walker run (walker.resolve_scout_dtype) into scout mode, so the
# whole suite — golden parity, checkpoint identity, streaming
# determinism — re-proves itself on the f32 path.
step "tier-1 suite under PPLS_SCOUT=1 (scout f32 lane)"
rm -f /tmp/_t1_scout.log
timeout -k 10 870 env JAX_PLATFORMS=cpu PPLS_SCOUT=1 \
    python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1_scout.log
rc=${PIPESTATUS[0]}
echo "SCOUT_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_t1_scout.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "ci: PPLS_SCOUT=1 lane FAILED (rc=$rc)"
    FAILURES=$((FAILURES + 1))
fi

# --- 1c. tier-1 again with the CHAOS lane armed (PPLS_CHAOS=1) ---
# Verify-on-write for every snapshot + supervisor-routed serve CLI:
# the integrity machinery runs on every checkpointed test instead of
# only the dedicated corruption tests.
step "tier-1 suite under PPLS_CHAOS=1 (checkpoint-integrity lane)"
rm -f /tmp/_t1_chaos.log
timeout -k 10 870 env JAX_PLATFORMS=cpu PPLS_CHAOS=1 \
    python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1_chaos.log
rc=${PIPESTATUS[0]}
echo "CHAOS_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_t1_chaos.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "ci: PPLS_CHAOS=1 lane FAILED (rc=$rc)"
    FAILURES=$((FAILURES + 1))
fi

# --- 2. packaging smoke: editable install + console script ---
if [ "${PPLS_CI_SKIP_INSTALL:-0}" != "1" ]; then
    step "pip install -e . smoke"
    # --no-build-isolation: air-gapped containers cannot fetch the
    # isolated build env's setuptools; the host install is fine
    if pip install -e . --no-deps --no-build-isolation -q; then
        if ppls-tpu --help > /dev/null 2>&1 \
                && ppls-tpu serve --help > /dev/null 2>&1; then
            echo "ci: ppls-tpu --help OK (serve subcommand included)"
        else
            echo "ci: ppls-tpu --help FAILED"
            FAILURES=$((FAILURES + 1))
        fi
    else
        echo "ci: pip install -e . FAILED"
        FAILURES=$((FAILURES + 1))
    fi
else
    echo "ci: install smoke skipped (PPLS_CI_SKIP_INSTALL=1)"
fi

# --- 3. artifact schema check: malformed blocks fail loudly ---
step "artifact schema check"
if python tools/check_artifacts.py; then
    echo "ci: artifacts OK"
else
    echo "ci: artifact schema check FAILED"
    FAILURES=$((FAILURES + 1))
fi

# --- 4. graftlint: project-specific static analysis (AST tier) ---
# New violations fail; grandfathered ones are enumerated in the
# committed baseline (tools/graftlint_baseline.json). See BASELINE.md
# "Static analysis & strict modes" for the rule set and the allowlist
# workflow.
step "graftlint static analysis (GL01-GL06 + GL11)"
if python -m tools.graftlint ppls_tpu \
        --baseline tools/graftlint_baseline.json --quiet; then
    echo "ci: graftlint OK"
else
    echo "ci: graftlint FAILED (new violations vs the baseline)"
    FAILURES=$((FAILURES + 1))
fi

# --- 4b. graftlint deep tier: traced-jaxpr semantic analysis ---
# The --deep run re-traces the real engine programs, so it carries a
# WALL BUDGET (240 s, ~15x the measured ~16 s: a runaway trace means a
# probe regressed into executing instead of tracing — that must fail
# CI, not wedge it). The JSON ledger is the machine-readable artifact
# (one record per violation) and is schema-gated like every other
# artifact document in this repo.
step "graftlint deep tier (GL07-GL10, traced jaxprs)"
GL_JSON="$(mktemp /tmp/ppls_ci_graftlint.XXXXXX.json)"
deep_t0=$SECONDS
if timeout -k 10 240 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m tools.graftlint ppls_tpu --deep \
        --baseline tools/graftlint_baseline.json \
        --format json > "$GL_JSON" \
        && python tools/check_artifacts.py --graftlint "$GL_JSON"; then
    echo "ci: graftlint deep OK ($((SECONDS - deep_t0))s of 240s budget)"
else
    echo "ci: graftlint deep tier FAILED (new semantic violations, "\
"schema-invalid ledger, or wall budget exceeded)"
    FAILURES=$((FAILURES + 1))
fi
rm -f "$GL_JSON"

# --- 4c. graftlint runtime tier: host-side serving-stack analysis ---
# GL12 (snapshot-surface completeness), GL13 (lock-order +
# blocking-under-lock), GL14 (thread-shared-state) are pure AST
# analysis — milliseconds — but carry a wall budget anyway (60 s,
# same runaway-means-regression logic as 4b) and the same
# schema-gated JSON ledger.
step "graftlint runtime tier (GL12-GL14, serving stack)"
GLR_JSON="$(mktemp /tmp/ppls_ci_graftlint_rt.XXXXXX.json)"
rt_t0=$SECONDS
if timeout -k 10 60 \
        python -m tools.graftlint ppls_tpu --runtime \
        --baseline tools/graftlint_baseline.json \
        --format json > "$GLR_JSON" \
        && python tools/check_artifacts.py --graftlint "$GLR_JSON"; then
    echo "ci: graftlint runtime OK ($((SECONDS - rt_t0))s of 60s budget)"
else
    echo "ci: graftlint runtime tier FAILED (new serving-stack "\
"violations, schema-invalid ledger, or wall budget exceeded)"
    FAILURES=$((FAILURES + 1))
fi
rm -f "$GLR_JSON"

# --- 5. serve telemetry smoke: seeded synthetic load + event log ---
# A short `ppls-tpu serve` run on the deterministic Poisson schedule
# (interpret-friendly sizing, same shape as tests/test_stream.py's
# CLI test) must produce a schema-valid --events timeline: the
# round-10 observability artifact is gated end-to-end, not just at
# the unit level.
step "serve --events telemetry smoke"
EV_FILE="$(mktemp /tmp/ppls_ci_events.XXXXXX.jsonl)"
if JAX_PLATFORMS=cpu python -m ppls_tpu serve \
        --synthetic 4 --arrival-rate 2 --seed 0 --eps 1e-6 \
        -a 1e-2 -b 1.0 --slots 8 --chunk 512 --capacity 65536 \
        --lanes 256 --refill-slots 2 \
        --events "$EV_FILE" > /dev/null 2>&1 \
        && python tools/check_artifacts.py --events "$EV_FILE"; then
    echo "ci: serve events OK"
else
    echo "ci: serve --events telemetry smoke FAILED"
    FAILURES=$((FAILURES + 1))
fi
rm -f "$EV_FILE"

# --- 5b. seeded chaos drain: committed fault plans must recover ---
step "serve --fault-plan chaos drain (hang + chip-loss + corrupt ckpt + NaN)"
CH_DIR="$(mktemp -d)"
chaos_fail=0
# stage 1: dd stream on the virtual 8-mesh — NaN poison (quarantine),
# injected hang (watchdog resume), chip loss (resize-resume onto 7)
# timeout wrapper: this stage INJECTS a hang — if the watchdog/
# supervisor plumbing it exists to test ever regresses, the hang must
# fail CI, not wedge it
if timeout -k 10 600 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m ppls_tpu serve \
        --engine walker-dd --n-devices 8 \
        --synthetic 6 --arrival-rate 2 --seed 0 --eps 1e-6 \
        -a 1e-2 -b 1.0 --slots 8 --chunk 256 --capacity 65536 \
        --lanes 256 --refill-slots 2 \
        --checkpoint "$CH_DIR/s1.ckpt" --checkpoint-every 1 \
        --watchdog 60 --events "$CH_DIR/s1.jsonl" \
        --fault-plan @tools/chaos_plan.json \
        > "$CH_DIR/s1.out" 2> "$CH_DIR/s1.err"; then
    python - "$CH_DIR/s1.out" <<'PYEOF' || chaos_fail=1
import json, sys
lines = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
s = lines[-1]
assert s.get("summary") and s.get("supervised"), "not supervised"
assert s["completed"] == 6, s["completed"]
assert s.get("failed") == 1, ("quarantine", s.get("failed"))
actions = [r["action"] for r in s["recoveries"]]
assert "resize_resume" in actions, actions      # chip loss recovered
assert "backoff_resume" in actions, actions     # hang recovered
kinds = {e["kind"] for e in s["faults_injected"]}
assert kinds == {"nan_poison", "hang", "chip_loss"}, kinds
print("ci: chaos stage 1 OK (quarantine + watchdog + resize-resume)")
PYEOF
else
    echo "ci: chaos stage 1 serve FAILED"
    chaos_fail=1
fi
# round 19: the chaos-drain timeline must also satisfy the
# rid-linkage contract (every trace event linked to an open request
# span; terminal events close their span — zero orphans)
python tools/check_artifacts.py --events "$CH_DIR/s1.jsonl" \
    --unbalanced-ok --rid-linkage || chaos_fail=1
# stage 2: snapshot corruption + phase-boundary crash — the resume
# must refuse the damaged file (CheckpointCorruptError) and self-heal
# by starting fresh
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m ppls_tpu serve \
        --synthetic 6 --arrival-rate 2 --seed 0 --eps 1e-6 \
        -a 1e-2 -b 1.0 --slots 8 --chunk 512 --capacity 65536 \
        --lanes 256 --refill-slots 2 \
        --checkpoint "$CH_DIR/s2.ckpt" --checkpoint-every 1 \
        --watchdog 120 --events "$CH_DIR/s2.jsonl" \
        --fault-plan @tools/chaos_plan_ckpt.json \
        > "$CH_DIR/s2.out" 2> "$CH_DIR/s2.err"; then
    python - "$CH_DIR/s2.out" "$CH_DIR/s2.err" <<'PYEOF' || chaos_fail=1
import json, sys
lines = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
s = lines[-1]
assert s.get("summary") and s.get("supervised"), "not supervised"
assert s["completed"] == 6, s["completed"]
kinds = {e["kind"] for e in s["faults_injected"]}
assert kinds == {"ckpt_corrupt", "crash"}, kinds
err = open(sys.argv[2]).read()
assert "starting fresh" in err, "corrupt-snapshot fresh start not taken"
print("ci: chaos stage 2 OK (corrupt snapshot -> fresh start)")
PYEOF
else
    echo "ci: chaos stage 2 serve FAILED"
    chaos_fail=1
fi
python tools/check_artifacts.py --events "$CH_DIR/s2.jsonl" \
    --unbalanced-ok --rid-linkage || chaos_fail=1
rm -rf "$CH_DIR"
if [ "$chaos_fail" -ne 0 ]; then
    echo "ci: seeded chaos drain FAILED"
    FAILURES=$((FAILURES + 1))
else
    echo "ci: seeded chaos drain OK"
fi

# --- 5c. chaos under load: overload + chip-loss + NaN poison ---
step "serve multi-tenant chaos under load (overload + chip-loss + NaN)"
OV_DIR="$(mktemp -d)"
ov_fail=0
if timeout -k 10 600 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m ppls_tpu serve \
        --engine walker-dd --n-devices 8 \
        --synthetic 12 --arrival-rate 8 --seed 0 --eps 1e-6 \
        -a 1e-2 -b 1.0 --slots 4 --chunk 256 --capacity 65536 \
        --lanes 256 --refill-slots 2 \
        --queue-limit 5 --tenants "free:2:0,std:1:1,pro:1:2" \
        --checkpoint "$OV_DIR/ov.ckpt" --checkpoint-every 1 \
        --watchdog 120 --events "$OV_DIR/ov.jsonl" \
        --fault-plan @tools/chaos_plan_overload.json \
        > "$OV_DIR/ov.out" 2> "$OV_DIR/ov.err"; then
    python - "$OV_DIR/ov.out" <<'PYEOF' || ov_fail=1
import json, sys
lines = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
s = lines[-1]
assert s.get("summary") and s.get("supervised"), "not supervised"
# the overload accounting invariant: every offered request either
# retired (quarantine included) or has an explicit shed record
assert s["completed"] + s["shed"] == 12, (s["completed"], s["shed"])
assert s["shed"] >= 1, "overload produced no sheds"
assert s.get("failed") == 1, ("quarantine", s.get("failed"))
actions = [r["action"] for r in s["recoveries"]]
assert "resize_resume" in actions, actions      # chip loss recovered
kinds = {e["kind"] for e in s["faults_injected"]}
assert kinds == {"nan_poison", "chip_loss"}, kinds
assert s["latency_by_class"], "no per-class SLO block"
shed_lines = [r for r in lines if r.get("shed") is True]
assert len(shed_lines) == s["shed"], "shed records != summary.shed"
assert all("tenant" in r and "reason" in r for r in shed_lines)
print("ci: chaos-under-load OK (shed + quarantine + resize-resume, "
      f"per-class SLO over {len(s['latency_by_class'])} classes)")
PYEOF
else
    echo "ci: chaos-under-load serve FAILED"
    ov_fail=1
fi
python tools/check_artifacts.py --serve "$OV_DIR/ov.out" \
    --events "$OV_DIR/ov.jsonl" --unbalanced-ok --rid-linkage \
    || ov_fail=1
# round 19: the offline critical-path analyzer must decompose the
# chaos-under-load timeline with components summing exactly to every
# recorded retire latency
JAX_PLATFORMS=cpu python tools/analyze_request.py "$OV_DIR/ov.jsonl" \
    --check > /dev/null || ov_fail=1
rm -rf "$OV_DIR"
if [ "$ov_fail" -ne 0 ]; then
    echo "ci: chaos under load FAILED"
    FAILURES=$((FAILURES + 1))
else
    echo "ci: chaos under load OK"
fi

# --- 5d. MULTICHIP process-count sweep (round 18) ---
# The local CPU cluster (real worker subprocesses behind the
# coordinator, runtime/cluster.py) must serve the identical dyadic
# workload at {1, 2, 4} processes with BIT-IDENTICAL per-request
# areas — the multi-process determinism contract, gated end-to-end
# through the serve CLI. Each run carries a wall budget (a wedged
# worker handshake must fail CI, not hang it); ledgers validate
# through check_artifacts --serve.
step "multi-process serve sweep (--processes {1,2,4})"
MP_DIR="$(mktemp -d)"
mp_fail=0
for P in 1 2 4; do
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m ppls_tpu serve \
            --processes "$P" --f64-rounds 2 --family quad_scaled \
            --theta "1.0,1.25,1.5,2.0,0.75,3.0" \
            --arrival-rate 2 --seed 0 --eps 1e-9 -a 0.0 -b 1.0 \
            --slots 4 --chunk 1024 --capacity 65536 \
            --lanes 256 --refill-slots 2 \
            > "$MP_DIR/p$P.out" 2> "$MP_DIR/p$P.err"; then
        python tools/check_artifacts.py --serve "$MP_DIR/p$P.out" \
            || mp_fail=1
    else
        echo "ci: --processes $P serve FAILED"
        mp_fail=1
    fi
done
python - "$MP_DIR" <<'PYEOF' || mp_fail=1
import glob
import json
import sys
areas = {}
for p in sorted(glob.glob(sys.argv[1] + "/p*.out")):
    recs = [json.loads(ln) for ln in open(p) if ln.strip()]
    s = recs[-1]
    assert s.get("summary") and s["completed"] == 6, (p, s)
    areas[p] = {r["rid"]: r["area"] for r in recs
                if "rid" in r and not r.get("summary")}
vals = list(areas.values())
assert len(vals) == 3 and len(vals[0]) == 6
assert all(v == vals[0] for v in vals[1:]), \
    "process-count sweep areas diverged"
print("ci: process sweep OK (6 areas bit-identical across "
      "{1,2,4} processes)")
PYEOF
# round 19: FEDERATED METRICS scraped LIVE during a --processes 2 run
# — every in-flight sample must parse, and the final (post-summary,
# inside the PPLS_SERVE_METRICS_HOLD window) sample must satisfy the
# reconciliation invariant: coordinator-merged retired counter ==
# sum over worker processes + spillover == summary.completed
python - "$MP_DIR" <<'PYEOF' || mp_fail=1
import json, os, re, subprocess, sys, time, urllib.request
d = sys.argv[1]
out_p, err_p = os.path.join(d, "fed.out"), os.path.join(d, "fed.err")
env = dict(os.environ, JAX_PLATFORMS="cpu",
           PPLS_SERVE_METRICS_HOLD="15")
cmd = [sys.executable, "-m", "ppls_tpu", "serve",
       "--processes", "2", "--f64-rounds", "2",
       "--family", "quad_scaled",
       "--theta", "1.0,1.25,1.5,2.0,0.75,3.0",
       "--arrival-rate", "2", "--seed", "0", "--eps", "1e-9",
       "-a", "0.0", "-b", "1.0", "--slots", "4",
       "--chunk", "1024", "--capacity", "65536",
       "--lanes", "256", "--refill-slots", "2",
       "--metrics-port", "0"]
with open(out_p, "w") as fo, open(err_p, "w") as fe:
    proc = subprocess.Popen(cmd, stdout=fo, stderr=fe, env=env)
try:
    url, deadline = None, time.monotonic() + 240
    while url is None and time.monotonic() < deadline:
        m = re.search(r"metrics on (http://\S+)", open(err_p).read())
        if m:
            url = m.group(1)
        elif proc.poll() is not None:
            raise SystemExit(f"serve died rc={proc.returncode}: "
                             + open(err_p).read()[-500:])
        else:
            time.sleep(0.2)
    samples, summary = 0, None
    while summary is None and time.monotonic() < deadline:
        with urllib.request.urlopen(url, timeout=10) as r:
            r.read()                       # every live sample parses
        samples += 1
        for ln in open(out_p).read().splitlines():
            if ln.strip().startswith("{"):
                rec = json.loads(ln)
                if rec.get("summary"):
                    summary = rec
        time.sleep(0.2)
    assert summary is not None, "no summary within budget"
    with urllib.request.urlopen(url, timeout=10) as r:
        expo = r.read().decode()
finally:
    proc.kill(); proc.wait(timeout=30)
vals = {}
for ln in expo.splitlines():
    m = re.match(r'ppls_stream_retired_total\{process="([^"]+)"\} '
                 r'(\S+)', ln)
    if m:
        vals[m.group(1)] = float(m.group(2))
workers = sum(v for k, v in vals.items() if k != "coordinator")
spill = summary.get("spillover", {}).get("spillover_completed", 0)
assert vals.get("coordinator") == summary["completed"], (vals, summary)
assert workers + spill == summary["completed"], (vals, spill, summary)
print(f"ci: federated /metrics OK ({samples} live scrapes; "
      f"coordinator {vals['coordinator']:.0f} == workers "
      f"{workers:.0f} + spillover {spill})")
PYEOF
rm -rf "$MP_DIR"
if [ "$mp_fail" -ne 0 ]; then
    echo "ci: multi-process sweep FAILED"
    FAILURES=$((FAILURES + 1))
else
    echo "ci: multi-process sweep OK"
fi

# --- 5e. HETEROGENEOUS DISPATCH under chaos (round 21) ---
# A deterministic mixed-shape workload (4 distinct engine keys: two
# eps bands x trapezoid, a simpson request, a theta-block-2 batch,
# plus one malformed line that must get a per-line rejection) through
# `serve --dispatch --supervise` with the committed crash plan
# (tools/chaos_plan_dispatch.json kills the WHOLE pool at the close
# edge of turn 1, right after that turn's coordinated cut). The
# supervisor must resume the EngineDispatcher from the manifest and
# drain; the summary must show the pool invariants — recompiles: 0
# across mixed shapes AND across the kill-and-resume, >= 3 engine
# keys actually spun up, per-engine completions reconciling with the
# total — and the ledger + events timeline validate through
# check_artifacts --serve / --events --rid-linkage.
step "serve --dispatch heterogeneous pool under chaos (crash + resume)"
HD_DIR="$(mktemp -d)"
hd_fail=0
cat > "$HD_DIR/reqs.jsonl" <<'EOF'
{"theta": 1.0, "bounds": [1e-2, 1.0], "arrival_phase": 0}
{"theta": 1.05, "bounds": [1e-2, 1.0], "eps": 1e-7, "arrival_phase": 0}
{"theta": 1.1, "bounds": [1e-2, 1.0], "rule": "simpson", "arrival_phase": 0}
{"theta": [1.15, 1.2], "bounds": [1e-2, 1.0], "arrival_phase": 1}
{"theta": 1.25, "bounds": [1e-2, 1.0], "arrival_phase": 1}
{"theta": 1.3, "bounds": [1e-2, 1.0], "eps": 1e-7, "arrival_phase": 2}
{"theta": 1.35, "bounds": [1e-2, 1.0], "rule": "simpson", "arrival_phase": 2}
{"theta": [1.4, 1.45], "bounds": [1e-2, 1.0], "arrival_phase": 3}
{"theta": 1.5, "bounds": [1e-2, 1.0], "eps": 1e-20}
EOF
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m ppls_tpu serve \
        --dispatch --max-engines 4 --supervise \
        --requests "$HD_DIR/reqs.jsonl" \
        --eps 1e-6 -a 1e-2 -b 1.0 --slots 4 --chunk 512 \
        --capacity 65536 --lanes 256 --refill-slots 2 \
        --checkpoint "$HD_DIR/hd.ckpt" --checkpoint-every 1 \
        --watchdog 120 --events "$HD_DIR/hd.jsonl" \
        --fault-plan @tools/chaos_plan_dispatch.json \
        > "$HD_DIR/hd.out" 2> "$HD_DIR/hd.err"; then
    python - "$HD_DIR/hd.out" <<'PYEOF' || hd_fail=1
import json, sys
lines = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
s = lines[-1]
assert s.get("summary") and s.get("supervised"), "not supervised"
assert s.get("dispatch") is True, "summary lacks the dispatch block"
# THE pool invariant: mixed-shape traffic + kill-and-resume, zero
# recompiles (every shape change is a pool ROUTE, never a recompile)
assert s["recompiles"] == 0, ("recompiles", s["recompiles"])
assert s["completed"] == 8, s["completed"]
keys = s["engines"]
assert len(keys) >= 3, ("engine keys", sorted(keys))
assert sum(e["completed"] for e in keys.values()) == 8, keys
assert s.get("attempts", 1) >= 2, "crash did not force a resume"
kinds = {e["kind"] for e in s["faults_injected"]}
assert kinds == {"crash"}, kinds
rej = [r for r in lines if r.get("rejected")]
assert len(rej) == 1 and "eps" in rej[0]["error"], rej
print(f"ci: hetero dispatch OK ({len(keys)} engine keys, "
      "recompiles 0 across crash-resume, malformed line rejected)")
PYEOF
else
    echo "ci: serve --dispatch chaos run FAILED"
    hd_fail=1
fi
python tools/check_artifacts.py --serve "$HD_DIR/hd.out" \
    --events "$HD_DIR/hd.jsonl" --unbalanced-ok --rid-linkage \
    || hd_fail=1
rm -rf "$HD_DIR"
if [ "$hd_fail" -ne 0 ]; then
    echo "ci: heterogeneous dispatch leg FAILED"
    FAILURES=$((FAILURES + 1))
else
    echo "ci: heterogeneous dispatch leg OK"
fi

# --- 5f. LEASE-ENABLED dispatch under chaos (round 22) ---
# The same mixed-shape workload through `serve --dispatch --lease
# --overlap-boundaries --supervise`, with the committed crash plan
# (tools/chaos_plan_dispatch_lease.json kills the pool at the close
# edge of turn 3 — AFTER lease grants landed at turns 1-2, so the
# ledger is in flight across the kill). The supervisor must resume,
# restore the lease ledger from the manifest, and drain; the summary
# must show recompiles: 0, a BALANCED ledger (every donated credit
# reconciles against a received one, donated >= 1 so the leg actually
# exercised leasing), and the rid-linked timeline must validate —
# lease grants are replayed schedule, not best-effort hints.
step "serve --dispatch --lease --overlap-boundaries under chaos"
LD_DIR="$(mktemp -d)"
ld_fail=0
cat > "$LD_DIR/reqs.jsonl" <<'EOF'
{"theta": 1.0, "bounds": [1e-2, 1.0], "arrival_phase": 0}
{"theta": 1.05, "bounds": [1e-2, 1.0], "eps": 1e-7, "arrival_phase": 0}
{"theta": 1.1, "bounds": [1e-2, 1.0], "rule": "simpson", "arrival_phase": 0}
{"theta": [1.15, 1.2], "bounds": [1e-2, 1.0], "arrival_phase": 1}
{"theta": 1.25, "bounds": [1e-2, 1.0], "arrival_phase": 1}
{"theta": 1.3, "bounds": [1e-2, 1.0], "eps": 1e-7, "arrival_phase": 2}
{"theta": 1.35, "bounds": [1e-2, 1.0], "rule": "simpson", "arrival_phase": 2}
{"theta": [1.4, 1.45], "bounds": [1e-2, 1.0], "arrival_phase": 3}
EOF
if timeout -k 10 600 env JAX_PLATFORMS=cpu python -m ppls_tpu serve \
        --dispatch --max-engines 4 --lease --overlap-boundaries \
        --supervise \
        --requests "$LD_DIR/reqs.jsonl" \
        --eps 1e-6 -a 1e-2 -b 1.0 --slots 4 --chunk 512 \
        --capacity 65536 --lanes 256 --refill-slots 2 \
        --checkpoint "$LD_DIR/ld.ckpt" --checkpoint-every 1 \
        --watchdog 120 --events "$LD_DIR/ld.jsonl" \
        --fault-plan @tools/chaos_plan_dispatch_lease.json \
        > "$LD_DIR/ld.out" 2> "$LD_DIR/ld.err"; then
    python - "$LD_DIR/ld.out" "$LD_DIR/ld.jsonl" <<'PYEOF' || ld_fail=1
import json, sys
lines = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
s = lines[-1]
assert s.get("summary") and s.get("supervised"), "not supervised"
assert s.get("dispatch") is True, "summary lacks the dispatch block"
assert s["recompiles"] == 0, ("recompiles", s["recompiles"])
assert s["completed"] == 8, s["completed"]
assert s.get("attempts", 1) >= 2, "crash did not force a resume"
L = s["leases"]
assert L["enabled"] and L["overlap_boundaries"], L
# the round-22 ledger invariant across kill-and-resume: every leased
# credit reconciles (donated == received), and the leg actually
# leased (>= 1) with at least one overlapped boundary recorded
assert L["donated"] >= 1, ("no leases exercised", L)
assert L["balanced"] and L["donated"] == L["received"], L
assert L["overlapped"] >= 1 and L["overlap_fraction"] > 0.0, L
grants = [json.loads(ln) for ln in open(sys.argv[2]) if ln.strip()]
grants = [e for e in grants
          if e.get("ev") == "event" and e.get("name") == "lease_grant"]
assert grants, "no lease_grant events in the timeline"
assert sum(g["attrs"]["credits"] for g in grants) == L["received"], \
    (len(grants), L["received"])
print(f"ci: lease dispatch OK (donated {L['donated']} == received, "
      f"{L['overlapped']}/{L['boundaries']} boundaries overlapped, "
      "recompiles 0 across crash-resume)")
PYEOF
else
    echo "ci: serve --dispatch --lease chaos run FAILED"
    ld_fail=1
fi
python tools/check_artifacts.py --serve "$LD_DIR/ld.out" \
    --events "$LD_DIR/ld.jsonl" --unbalanced-ok --rid-linkage \
    || ld_fail=1
rm -rf "$LD_DIR"
if [ "$ld_fail" -ne 0 ]; then
    echo "ci: lease-enabled dispatch leg FAILED"
    FAILURES=$((FAILURES + 1))
else
    echo "ci: lease-enabled dispatch leg OK"
fi

# --- 6. bench observatory: trajectory check + quick-proxy gate ---
# tools/bench_history.py --check normalizes the committed
# BENCH_r*/MULTICHIP_r* wrappers into one trajectory and fails on
# malformed rounds; --gate-run re-measures the quick walker proxy leg
# (device-counted, deterministic in interpret mode) and fails when it
# regresses past the stated tolerance vs tools/bench_quick_ref.json.
step "bench history check + quick-proxy regression gate"
if JAX_PLATFORMS=cpu python tools/bench_history.py --check \
        && JAX_PLATFORMS=cpu python tools/bench_history.py --gate-run; then
    echo "ci: bench history + gate OK"
else
    echo "ci: bench history / regression gate FAILED"
    FAILURES=$((FAILURES + 1))
fi

# --- 6b. many-theta amortization leg: record must schema-validate ---
# `bench.py theta --quick` (round 13) measures the bookkeeping-per-
# theta reduction at T in {32, 256}; its record is gated through the
# artifact schema so a malformed theta leg cannot silently drop from
# a future round's trajectory. (The reduction floor itself is gated by
# step 6's --gate-run via the theta block of bench_quick_ref.json.)
step "bench theta --quick artifact check"
if JAX_PLATFORMS=cpu python bench.py theta --quick \
        | python tools/check_artifacts.py -; then
    echo "ci: bench theta artifact OK"
else
    echo "ci: bench theta artifact FAILED"
    FAILURES=$((FAILURES + 1))
fi

# --- 6b2. closed-loop autotuning leg (round 20) ---
# `bench.py tune --quick` runs the budgeted staged sweep into a THROW-
# AWAY table (the committed tools/tuning_table.json is re-recorded
# only via the documented full-budget `python bench.py tune` +
# `--update-ref` flow, never silently by CI); the record validates
# through the bench envelope and BOTH tables — the fresh sweep output
# and the committed one — pass check_artifacts --tuning. The
# committed table's performance floor (tuned Pareto-beats the hand
# default on >= 2 families) is held by step 6's --gate-run via
# bench_history's gate_tuning_record.
step "bench tune --quick sweep + tuning-table schema check"
TUNE_TABLE="$(mktemp /tmp/ppls_ci_tune.XXXXXX.json)"
if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench.py tune \
        --quick --out "$TUNE_TABLE" \
        | python tools/check_artifacts.py - \
        && python tools/check_artifacts.py \
            --tuning "$TUNE_TABLE" \
            --tuning tools/tuning_table.json; then
    echo "ci: bench tune artifact + tuning tables OK"
else
    echo "ci: bench tune leg FAILED"
    FAILURES=$((FAILURES + 1))
fi
rm -f "$TUNE_TABLE"

# --- 6c. multi-host resilience leg: record must schema-validate ---
# `bench.py multihost` (round 18) kills one host of a real 2-process
# cluster under overload and records redeal wall + spillover-engaged
# fraction + the zero-lost-acks/bit-identity invariants; the record
# is gated through the artifact schema and the leg's own acceptance
# booleans (exit nonzero when spillover failed to engage or areas
# diverged). The proxy bands themselves are held by step 6's
# --gate-run via the multihost block of bench_quick_ref.json.
step "bench multihost artifact check"
if timeout -k 10 300 env JAX_PLATFORMS=cpu python bench.py multihost \
        | python tools/check_artifacts.py -; then
    echo "ci: bench multihost artifact OK"
else
    echo "ci: bench multihost artifact FAILED"
    FAILURES=$((FAILURES + 1))
fi

# --- 7. C hygiene: csrc must compile warning-free ---
# The stub-linked MPI binary is part of the tier-1 surface
# (test_backend.py runs the real farmer/worker protocol through it),
# so warnings in csrc are latent test-lane breakage.
step "C hygiene (-Wall -Wextra -Werror)"
CC_BIN="${CC:-}"
if [ -z "$CC_BIN" ]; then
    for c in cc gcc clang; do
        if command -v "$c" > /dev/null 2>&1; then CC_BIN="$c"; break; fi
    done
fi
if [ -z "$CC_BIN" ]; then
    echo "ci: NOTICE - no C compiler found (cc/gcc/clang); skipping" \
         "the csrc hygiene step"
else
    CSRC="ppls_tpu/backends/csrc"
    CH_DIR="$(mktemp -d)"
    ch_fail=0
    "$CC_BIN" -Wall -Wextra -Werror -O2 -DAQ_MPI_STUB -pthread \
        -c "$CSRC/aquad_mpi.c" -o "$CH_DIR/mpi_stub.o" || ch_fail=1
    "$CC_BIN" -Wall -Wextra -Werror -O2 \
        -c "$CSRC/aquad_seq.c" -o "$CH_DIR/seq.o" || ch_fail=1
    rm -rf "$CH_DIR"
    if [ "$ch_fail" -ne 0 ]; then
        echo "ci: C hygiene FAILED (warnings under -Wall -Wextra -Werror)"
        FAILURES=$((FAILURES + 1))
    else
        echo "ci: C hygiene OK ($CC_BIN, stub + seq translation units)"
    fi
fi

echo
if [ "$FAILURES" -ne 0 ]; then
    echo "ci: $FAILURES step(s) FAILED"
    exit 1
fi
echo "ci: all steps green"
