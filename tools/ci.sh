#!/usr/bin/env bash
# One-command CI for this repo (toolchain-less CPU container):
#
#   1. tier-1 forced-CPU test suite (the ROADMAP gate, verbatim)
#   2. `pip install -e .` smoke + `ppls-tpu --help` console script
#   3. artifact schema check (BENCH_r*/MULTICHIP_r* round JSONs)
#
# Usage: bash tools/ci.sh            # from anywhere inside the repo
#        PPLS_CI_SKIP_INSTALL=1 bash tools/ci.sh   # tests + schema only
set -u -o pipefail

ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$ROOT"
FAILURES=0

step() { echo; echo "=== ci: $* ==="; }

# --- 1. tier-1 suite (keep in sync with ROADMAP.md "Tier-1 verify") ---
step "tier-1 forced-CPU test suite"
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "ci: tier-1 suite FAILED (rc=$rc)"
    FAILURES=$((FAILURES + 1))
fi

# --- 2. packaging smoke: editable install + console script ---
if [ "${PPLS_CI_SKIP_INSTALL:-0}" != "1" ]; then
    step "pip install -e . smoke"
    # --no-build-isolation: air-gapped containers cannot fetch the
    # isolated build env's setuptools; the host install is fine
    if pip install -e . --no-deps --no-build-isolation -q; then
        if ppls-tpu --help > /dev/null 2>&1 \
                && ppls-tpu serve --help > /dev/null 2>&1; then
            echo "ci: ppls-tpu --help OK (serve subcommand included)"
        else
            echo "ci: ppls-tpu --help FAILED"
            FAILURES=$((FAILURES + 1))
        fi
    else
        echo "ci: pip install -e . FAILED"
        FAILURES=$((FAILURES + 1))
    fi
else
    echo "ci: install smoke skipped (PPLS_CI_SKIP_INSTALL=1)"
fi

# --- 3. artifact schema check: malformed blocks fail loudly ---
step "artifact schema check"
if python tools/check_artifacts.py; then
    echo "ci: artifacts OK"
else
    echo "ci: artifact schema check FAILED"
    FAILURES=$((FAILURES + 1))
fi

echo
if [ "$FAILURES" -ne 0 ]; then
    echo "ci: $FAILURES step(s) FAILED"
    exit 1
fi
echo "ci: all steps green"
