"""graftlint — project-specific static analysis for the ppls_tpu repro.

The correctness contract this repo defends is tiny and absolute
(``Area=7583461.801486``, 6567 tasks, bit-for-bit — PAPER.md), and the
recurring bug classes that threaten it across ~14k LoC of jitted,
sharded, streaming JAX are all *statically visible*:

* GL01 — a carry field missing from the checkpoint identity surface
  (the PR-2 ``refill_slots`` near-miss: resume silently blends runs);
* GL02 — dtype-less array creation / f32 leakage in f64 accumulator
  paths (silent downcasts move the final bit);
* GL03 — host syncs (``jax.device_get``, ``np.asarray``, ``int()`` on
  traced values) inside functions reachable from a jitted root;
* GL04 — collectives in the dd engine not paired with a ``crounds``
  increment (corrupts the device-counted collective-round claims);
* GL05 — static-arg drift on jitted entries (missing statics trace
  config into the program; loop-varying statics recompile per call);
* GL06 — telemetry publishes (obs registry/span emits) inside
  functions reachable from a jitted root: the side effect fires at
  trace time (phantom samples) and its inputs force a host sync —
  publishes belong in the boundary hooks that already hold the
  fetched values;
* GL11 — lock discipline (round 17): reads/writes of declared
  cross-thread attributes (the serving runtime's shared engine
  handle) outside the owning ``with``-lock block — regression armor
  for the two PR-10 ingest races.

Round 17 adds the SEMANTIC tier (``--deep``, ``deep.py``): GL07-GL10
trace the real jitted engine programs on CPU (tracing executes
nothing) and walk the captured jaxprs — collective census vs the
crounds model, f32→f64 origin audit, host-interop census, and
jaxpr-hash compile-once stability. The AST rules live one module per
concern under ``rules/``.

Violations are keyed ``CODE:path:symbol`` (no line numbers, so edits
elsewhere in a file don't churn the baseline) and grandfathered sites
live in a committed allowlist (``tools/graftlint_baseline.json``) with
a reason per entry.  ``python -m tools.graftlint ppls_tpu --baseline
tools/graftlint_baseline.json`` fails only on NEW violations;
``--prune-stale`` shrinks the allowlist, ``--format json`` emits the
machine-readable ledger CI gates through ``check_artifacts
--graftlint``.
"""

from tools.graftlint.core import (  # noqa: F401
    LintModule,
    Violation,
    load_baseline,
    run_lint,
    split_new_and_known,
)
from tools.graftlint.rules import ALL_RULES  # noqa: F401
