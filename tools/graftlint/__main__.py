"""CLI: ``python -m tools.graftlint ppls_tpu [--baseline FILE]``.

Exit status 1 iff there are NEW violations (not in the baseline).
Grandfathered violations are enumerated (they are debt, not noise);
stale baseline entries (fixed sites still allowlisted) are reported so
the baseline shrinks over time instead of fossilizing.
"""

from __future__ import annotations

import argparse
import sys

from tools.graftlint.core import (load_baseline, run_lint,
                                  split_new_and_known, write_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="project-specific static analysis (GL01-GL06)")
    ap.add_argument("target",
                    help="package directory to lint (single files are "
                         "rejected: the rules are cross-module)")
    ap.add_argument("--baseline", default=None,
                    help="committed allowlist JSON; only violations "
                         "absent from it fail the run")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current "
                         "violations (preserves existing reasons)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the grandfathered listing")
    args = ap.parse_args(argv)

    try:
        violations = run_lint(args.target)
    except ValueError as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2
    baseline = load_baseline(args.baseline)

    if args.write_baseline:
        if not args.baseline:
            ap.error("--write-baseline requires --baseline")
        write_baseline(args.baseline, violations, reasons=baseline)
        print(f"graftlint: wrote {len({v.key for v in violations})} "
              f"grandfathered entries to {args.baseline}")
        return 0

    new, known, stale = split_new_and_known(violations, baseline)
    if known and not args.quiet:
        print(f"graftlint: {len(known)} grandfathered violation(s) "
              f"(allowlisted in {args.baseline}):")
        for v in known:
            reason = baseline.get(v.key, "")
            tail = f"  [allowlisted: {reason}]" if reason else ""
            print(f"  {v.render()}{tail}")
    if stale:
        print(f"graftlint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (site fixed — "
              f"remove from the allowlist):")
        for k in stale:
            print(f"  {k}")
    if new:
        print(f"graftlint: {len(new)} NEW violation(s):")
        for v in new:
            print(f"  {v.render()}")
        print("graftlint: FAIL (fix the sites above, or — for a "
              "reviewed, deliberate exception — add them to the "
              "baseline with a reason)")
        return 1
    print(f"graftlint: OK ({len(violations)} total, "
          f"{len(known)} grandfathered, 0 new)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
