"""CLI: ``python -m tools.graftlint ppls_tpu [--baseline FILE]
[--deep] [--runtime] [--since REF] [--format json] [--prune-stale]``.

Exit status 1 iff there are NEW violations (not in the baseline).
Grandfathered violations are enumerated (they are debt, not noise);
stale baseline entries (fixed sites still allowlisted) are reported so
the baseline shrinks over time instead of fossilizing —
``--prune-stale`` performs that shrink in one command.

``--deep`` adds the semantic tier (GL07-GL10, ``deep.py``): the real
jitted engine programs are traced on CPU (interpret mode, virtual
8-mesh for dd) and their jaxprs walked. ``--runtime`` adds the
host-runtime tier (GL12-GL14, ``runtime.py``): pure inter-procedural
AST analysis of the serving stack (snapshot-surface completeness,
lock-order/blocking-under-lock, thread-shared-state) — milliseconds,
no tracing, works on any package. Staleness is scoped to the tiers
that ran: a grandfathered deep or runtime entry is not reported stale
by an AST-only run. A symbol flagged by two tiers under one key is
reported ONCE (first tier wins).

``--since REF`` narrows the REPORT to files changed vs the git ref
(the lint still runs over the whole package — the rules are
cross-module), so a pre-commit hook sees only its own files.

``--format json`` emits one machine-readable record per violation
(schema-gated by ``tools/check_artifacts.py --graftlint``) so CI can
turn findings into annotations instead of grepping text.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.graftlint.core import (changed_paths_since, filter_to_changed,
                                  load_baseline, merge_tier,
                                  prune_stale_entries, run_lint,
                                  split_new_and_known,
                                  violations_to_json, write_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="project-specific static analysis (GL01-GL06 + "
                    "GL11; --deep adds the traced-jaxpr tier "
                    "GL07-GL10; --runtime adds the host-runtime "
                    "tier GL12-GL14)")
    ap.add_argument("target",
                    help="package directory to lint (single files are "
                         "rejected: the rules are cross-module)")
    ap.add_argument("--baseline", default=None,
                    help="committed allowlist JSON; only violations "
                         "absent from it fail the run")
    ap.add_argument("--deep", action="store_true",
                    help="also run the semantic tier (GL07-GL10): "
                         "trace the real jitted engine programs and "
                         "walk the captured jaxprs (ppls_tpu only)")
    ap.add_argument("--runtime", action="store_true",
                    help="also run the host-runtime tier (GL12-GL14): "
                         "snapshot-surface completeness, lock-order/"
                         "blocking-under-lock, thread-shared-state "
                         "(pure AST, any package)")
    ap.add_argument("--since", default=None, metavar="REF",
                    help="report only violations in files changed vs "
                         "the git ref (lint still runs over the whole "
                         "package; baseline/stale semantics unchanged)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text", dest="fmt",
                    help="json = one machine-readable record per "
                         "violation on stdout (exit codes unchanged)")
    ap.add_argument("--prune-stale", action="store_true",
                    help="rewrite --baseline dropping entries whose "
                         "sites are fixed (shrink-only; preserves "
                         "_comment blocks and surviving reasons)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current "
                         "violations (preserves existing reasons)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the grandfathered listing")
    args = ap.parse_args(argv)

    try:
        violations = run_lint(args.target)
    except ValueError as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2
    from tools.graftlint.rules import AST_CODES
    codes_checked = list(AST_CODES)
    if args.deep:
        import os
        if os.path.basename(os.path.normpath(args.target)) \
                != "ppls_tpu":
            print("graftlint: error: --deep traces the committed "
                  "engine programs and only applies to the ppls_tpu "
                  "package", file=sys.stderr)
            return 2
        from tools.graftlint.deep import DEEP_CODES, run_deep
        violations = merge_tier(violations, run_deep())
        codes_checked += list(DEEP_CODES)
    if args.runtime:
        from tools.graftlint.runtime import RUNTIME_CODES, run_runtime
        violations = merge_tier(violations, run_runtime(args.target))
        codes_checked += list(RUNTIME_CODES)
    baseline = load_baseline(args.baseline)

    if args.write_baseline:
        if not args.baseline:
            ap.error("--write-baseline requires --baseline")
        # codes_checked: an AST-only regeneration must carry the
        # grandfathered deep-tier entries forward, not delete them
        write_baseline(args.baseline, violations, reasons=baseline,
                       codes_checked=codes_checked)
        print(f"graftlint: wrote {len({v.key for v in violations})} "
              f"grandfathered entries to {args.baseline}")
        return 0

    new, known, stale = split_new_and_known(violations, baseline,
                                            codes_checked)
    if args.since:
        # narrow the REPORT (and the exit status) to the changed
        # files; staleness stays full-run — a stale entry is about
        # the baseline, not about any file in this diff
        try:
            changed = changed_paths_since(args.since)
        except ValueError as e:
            print(f"graftlint: error: {e}", file=sys.stderr)
            return 2
        new = filter_to_changed(new, changed)
        known = filter_to_changed(known, changed)
    if args.prune_stale:
        if not args.baseline:
            ap.error("--prune-stale requires --baseline")
        dropped = prune_stale_entries(args.baseline, stale)
        # the notice goes to stderr under --format json: stdout is
        # the machine-readable ledger and must stay parseable
        print(f"graftlint: pruned {dropped} stale baseline entr"
              f"{'y' if dropped == 1 else 'ies'} from "
              f"{args.baseline}",
              file=sys.stderr if args.fmt == "json" else sys.stdout)
        stale = []

    if args.fmt == "json":
        print(json.dumps(violations_to_json(
            args.target, new, known, stale, baseline,
            deep=args.deep, runtime=args.runtime), indent=1))
        return 1 if new else 0

    if known and not args.quiet:
        print(f"graftlint: {len(known)} grandfathered violation(s) "
              f"(allowlisted in {args.baseline}):")
        for v in known:
            reason = baseline.get(v.key, "")
            tail = f"  [allowlisted: {reason}]" if reason else ""
            print(f"  {v.render()}{tail}")
    if stale:
        print(f"graftlint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (site fixed — "
              f"remove from the allowlist, or run --prune-stale):")
        for k in stale:
            print(f"  {k}")
    if new:
        print(f"graftlint: {len(new)} NEW violation(s):")
        for v in new:
            print(f"  {v.render()}")
        print("graftlint: FAIL (fix the sites above, or — for a "
              "reviewed, deliberate exception — add them to the "
              "baseline with a reason)")
        return 1
    tiers_note = "".join(
        f", {t} tier clean" for t, on in
        (("deep", args.deep), ("runtime", args.runtime)) if on)
    print(f"graftlint: OK ({len(violations)} total, "
          f"{len(known)} grandfathered, 0 new{tiers_note})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
