"""graftlint core: module loading, violation model, baseline handling.

A *rule* is a callable ``rule(modules: list[LintModule]) ->
Iterable[Violation]`` operating on parsed ASTs of the whole target
package at once (GL01/GL03 are cross-function and cross-module checks,
so rules see everything, not one file at a time).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple  # noqa: F401


@dataclasses.dataclass(frozen=True)
class Violation:
    code: str        # "GL01".."GL05"
    path: str        # target-relative posix path of the offending file
    line: int        # 1-based line (display only — NOT part of the key)
    symbol: str      # stable anchor: "func", "Class.field", "func:detail"
    message: str     # fixer-friendly: what is wrong and what to do

    @property
    def key(self) -> str:
        """Baseline identity. Deliberately line-free: grandfathered
        sites must survive unrelated edits above them."""
        return f"{self.code}:{self.path}:{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.code} [{self.symbol}] "
                f"{self.message}")


@dataclasses.dataclass
class LintModule:
    """One parsed source file plus its intra-package import bindings."""

    path: str                # target-relative posix path
    tree: ast.Module
    source: str
    # name -> package-relative module path ("parallel/walker") for
    # `from ppls_tpu.parallel import walker` / `import ... as` aliases
    module_aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    # local name -> (module path, original name) for
    # `from ppls_tpu.parallel.walker import _breed as b`
    name_imports: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict)

    @property
    def modkey(self) -> str:
        """Package-relative module key: "parallel/walker"."""
        p = self.path
        if p.endswith("__init__.py"):
            p = p[: -len("__init__.py")] + "__init__"
        elif p.endswith(".py"):
            p = p[:-3]
        parts = p.split("/")
        return "/".join(parts[1:]) if len(parts) > 1 else parts[0]


def _resolve_pkg_module(dotted: str, pkg_name: str) -> Optional[str]:
    """'ppls_tpu.parallel.walker' -> 'parallel/walker' (None if not in
    the linted package)."""
    parts = dotted.split(".")
    if parts[0] != pkg_name:
        return None
    return "/".join(parts[1:]) if len(parts) > 1 else "__init__"


def _collect_imports(mod: LintModule, pkg_name: str) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                target = _resolve_pkg_module(alias.name, pkg_name)
                if target is not None:
                    mod.module_aliases[alias.asname
                                       or alias.name.split(".")[-1]] = target
        elif isinstance(node, ast.ImportFrom):
            if node.module is None or node.level:
                continue          # relative imports are not used here
            base = _resolve_pkg_module(node.module, pkg_name)
            if base is None:
                continue
            for alias in node.names:
                local = alias.asname or alias.name
                # `from ppls_tpu.parallel import walker` imports a
                # MODULE; `from ...walker import _breed` imports a name.
                sub = (f"{base}/{alias.name}" if base != "__init__"
                       else alias.name)
                mod.module_aliases.setdefault(local, sub)
                mod.name_imports[local] = (base, alias.name)


def load_package(target: str) -> List[LintModule]:
    """Parse every .py under ``target`` (a package directory). Paths in
    violations are relative to the target's parent, so
    "ppls_tpu/parallel/walker.py" reads naturally from the repo root.

    Single files are rejected: the rules are cross-module (GL01 needs
    ``runtime/checkpoint.py``'s surface, GL03 the import graph) and
    path-scoped (GL02/GL04), so a lone-file lint would silently skip
    most of them and report a false clean."""
    target = os.path.normpath(target)
    if os.path.isfile(target):
        raise ValueError(
            f"graftlint target must be a package directory, got the "
            f"file {target!r}: the rules are cross-module and "
            f"path-scoped — lint the package root instead")
    root = os.path.dirname(target) or "."
    files: List[str] = []
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__", "build")]
        files.extend(os.path.join(dirpath, f)
                     for f in sorted(filenames) if f.endswith(".py"))
    pkg_name = os.path.basename(target.rstrip("/"))
    modules = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        mod = LintModule(path=rel, tree=ast.parse(src, filename=f),
                         source=src)
        _collect_imports(mod, pkg_name)
        modules.append(mod)
    return modules


# --- inline pragma suppression ---------------------------------------------

def _pragma_lines(mod: LintModule) -> Dict[int, set]:
    """Lines carrying ``# graftlint: GL02 (reason)`` (or
    ``# graftlint: off``) pragmas -> set of suppressed codes ({"*"}
    for off). Only the directive part before the first ``(`` counts:
    a parenthesized reason like ``(off the hot path)`` must not
    escalate a single-rule pragma to suppress everything."""
    out: Dict[int, set] = {}
    for i, line in enumerate(mod.source.splitlines(), start=1):
        if "graftlint:" not in line:
            continue
        directive = line.split("graftlint:", 1)[1].split("(", 1)[0]
        codes = {t.strip(" ,").upper() for t in directive.split()
                 if t.strip(" ,")}
        out[i] = {"*"} if "OFF" in codes else {c for c in codes
                                               if c.startswith("GL")}
    return out


def run_lint(target: str, rules=None) -> List[Violation]:
    from tools.graftlint.rules import ALL_RULES
    modules = load_package(target)
    pragmas = {m.path: _pragma_lines(m) for m in modules}
    out: List[Violation] = []
    for rule in (rules if rules is not None else ALL_RULES):
        for v in rule(modules):
            suppressed = pragmas.get(v.path, {}).get(v.line, set())
            if "*" in suppressed or v.code in suppressed:
                continue
            out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.code, v.symbol))
    return out


# --- tiers ------------------------------------------------------------------

# code -> tier, kept in core (the rules/deep/runtime modules all
# import core, so the authoritative map lives below them; the tier
# modules' CODE tuples are pinned against this map by tests)
_TIER_OF_CODE: Dict[str, str] = {
    **{c: "ast" for c in ("GL01", "GL02", "GL03", "GL04", "GL05",
                          "GL06", "GL11")},
    **{c: "deep" for c in ("GL07", "GL08", "GL09", "GL10")},
    **{c: "runtime" for c in ("GL12", "GL13", "GL14")},
}


def tier_of(code: str) -> str:
    """Which tier owns a rule code ("ast" | "deep" | "runtime").
    Unknown codes map to "ast" — a new rule starts life in the always-
    on tier unless it registers here."""
    return _TIER_OF_CODE.get(code, "ast")


def merge_tier(violations: List[Violation],
               extra: Iterable[Violation]) -> List[Violation]:
    """Append another tier's findings, DEDUPING by key: a symbol
    flagged by two tiers (the keys are line-free, so one site can
    satisfy two rules' patterns) must appear once in the combined
    report — the first tier to flag it wins, later tiers add only
    genuinely new keys. Returns the re-sorted combined list."""
    seen = {v.key for v in violations}
    merged = list(violations)
    for v in extra:
        if v.key in seen:
            continue
        seen.add(v.key)
        merged.append(v)
    merged.sort(key=lambda v: (v.path, v.line, v.code, v.symbol))
    return merged


# --- --since (changed-only reporting) ---------------------------------------

def changed_paths_since(ref: str, cwd: str = ".") -> set:
    """Repo-relative posix paths changed vs ``ref``: committed,
    staged, and worktree changes (``git diff --name-only``) plus
    untracked files — the pre-commit working set."""
    import subprocess
    paths: set = set()
    for cmd in (["git", "diff", "--name-only", ref, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        res = subprocess.run(cmd, cwd=cwd, capture_output=True,
                             text=True)
        if res.returncode != 0:
            raise ValueError(
                f"--since: {' '.join(cmd)} failed: "
                f"{res.stderr.strip() or 'unknown git error'}")
        paths |= {line.strip().replace(os.sep, "/")
                  for line in res.stdout.splitlines() if line.strip()}
    return paths


def filter_to_changed(violations: List[Violation],
                      changed: Iterable[str]) -> List[Violation]:
    """Keep only violations in the changed-file set. The LINT still
    runs over the whole package (the rules are cross-module — a
    partial parse would false-clean, per :func:`load_package`); only
    the REPORT narrows, so ``--since`` keeps full-run semantics for
    baseline and staleness while a pre-commit hook sees just the
    files it is committing."""
    changed = set(changed)
    return [v for v in violations if v.path in changed]


# --- baseline ---------------------------------------------------------------

def load_baseline(path: Optional[str]) -> Dict[str, str]:
    """Committed allowlist: violation key -> reason string."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {e["key"]: e.get("reason", "") for e in data["grandfathered"]}


def write_baseline(path: str, violations: Iterable[Violation],
                   reasons: Optional[Dict[str, str]] = None,
                   codes_checked: Optional[Iterable[str]] = None
                   ) -> None:
    """Regenerate the baseline from the current violations.

    ``codes_checked`` scopes the regeneration to the rules that ran,
    mirroring :func:`split_new_and_known`'s staleness scoping: an
    AST-only ``--write-baseline`` must carry the committed file's
    grandfathered DEEP entries (GL07-GL10) forward verbatim — their
    rules never looked this run, so regenerating from the AST-only
    violation list alone would silently delete reviewed exceptions
    and fail the next ``--deep`` run. None = regenerate everything
    (the historical behavior)."""
    reasons = reasons or {}
    # regeneration must not destroy the committed file's documentation
    # (_comment block) or any other top-level keys
    doc: Dict[str, object] = {"version": 1}
    old_entries: List[Dict] = []
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as fh:
                old = json.load(fh)
            doc.update({k: v for k, v in old.items()
                        if k != "grandfathered"})
            old_entries = list(old.get("grandfathered", []))
        except (OSError, ValueError):
            pass
    entries = []
    seen = set()
    for v in violations:
        if v.key in seen:
            continue
        seen.add(v.key)
        entries.append({"key": v.key,
                        "tier": tier_of(v.code),
                        "reason": reasons.get(v.key, ""),
                        "message": v.message})
    if codes_checked is not None:
        checked = set(codes_checked)
        entries += [e for e in old_entries
                    if e.get("key", "").split(":", 1)[0] not in checked
                    and e.get("key") not in seen]
    doc["grandfathered"] = entries
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")


def split_new_and_known(violations: List[Violation],
                        baseline: Dict[str, str],
                        codes_checked: Optional[Iterable[str]] = None
                        ) -> Tuple[List[Violation], List[Violation],
                                   List[str]]:
    """-> (new, grandfathered, stale_baseline_keys).

    ``codes_checked`` scopes STALENESS to the rules that actually ran:
    with the deep tier off, a grandfathered GL07-GL10 entry is not
    "stale" (its rule never looked), so an AST-only run must neither
    fail on it nor invite its removal. None = every baseline key is in
    scope (the historical behavior)."""
    keys = {v.key for v in violations}
    new = [v for v in violations if v.key not in baseline]
    known = [v for v in violations if v.key in baseline]
    if codes_checked is None:
        in_scope = baseline.keys()
    else:
        checked = set(codes_checked)
        in_scope = [k for k in baseline
                    if k.split(":", 1)[0] in checked]
    stale = sorted(k for k in in_scope if k not in keys)
    return new, known, stale


def prune_stale_entries(path: str, stale: Iterable[str]) -> int:
    """--prune-stale: rewrite the committed baseline DROPPING the given
    stale keys — the shrink-only contract as one command instead of a
    hand edit. Preserves every other top-level key (the ``_comment``
    policy block included) and the surviving entries verbatim (their
    reasons and any per-entry ``_comment`` fields). Returns the number
    of entries dropped; never adds anything."""
    stale = set(stale)
    if not stale:
        return 0
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    before = doc.get("grandfathered", [])
    doc["grandfathered"] = [e for e in before
                            if e.get("key") not in stale]
    dropped = len(before) - len(doc["grandfathered"])
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    return dropped


def violations_to_json(target: str, new: List[Violation],
                       known: List[Violation], stale: List[str],
                       baseline: Dict[str, str], deep: bool,
                       runtime: bool = False) -> Dict:
    """The ``--format json`` document: one record per violation,
    machine-readable for CI annotations (schema:
    ``ppls_tpu.utils.artifact_schema.validate_graftlint_json``, gated
    by ``tools/check_artifacts.py --graftlint``)."""
    def rec(v: Violation, grandfathered: bool) -> Dict:
        d = {"key": v.key, "code": v.code, "tier": tier_of(v.code),
             "path": v.path, "line": v.line, "symbol": v.symbol,
             "message": v.message, "grandfathered": grandfathered}
        if grandfathered:
            d["reason"] = baseline.get(v.key, "")
        return d

    return {
        "schema": "graftlint-v1",
        "target": target,
        "deep": bool(deep),
        "runtime": bool(runtime),
        "violations": ([rec(v, False) for v in new]
                       + [rec(v, True) for v in known]),
        "stale": list(stale),
        "counts": {"total": len(new) + len(known), "new": len(new),
                   "grandfathered": len(known), "stale": len(stale)},
        "ok": not new,
    }
