"""graftlint --deep: jaxpr-level semantic analysis of the real engines.

The AST tier (GL01-GL06, GL11) polices what the SOURCE spells; the
invariants that actually break this codebase live in the *traced
programs* — a collective hidden behind a ``shard_map`` body builder or
a ``lax.cond`` branch is invisible to GL04, an f32 leak shows up as a
``convert_element_type`` edge no regex can see, and an accidental
static only exists after tracing. This tier traces the real jitted
engine programs — ``walker._run_cycles``, ``run_stream_cycle``,
``build_dd_walker_run`` in both dd modes, plus the bag and
XLA-boundary wavefront engines — on the CPU interpret path (virtual
8-mesh for dd; tracing never executes anything) and walks the
captured jaxprs:

* **GL07 — collective census.** Every ``psum``/``all_gather``/...
  primitive tracing captured must reconcile with the declared crounds
  accounting model (``GL07_CROUNDS_MODEL``): the semantic twin of
  GL04. Excess = an uncounted collective (the device-counted
  collective-round claims are silently false); deficit = a stale
  model entry (update it — the model shrinks like the baseline).
  Single-chip programs must census EMPTY unconditionally.
* **GL08 — dtype-flow audit.** Every f32→f64
  ``convert_element_type`` edge feeding the f64 credit path must
  originate inside the DECLARED dtype surface
  (``GL08_DTYPE_SURFACE``: the ds-limb modules, the scout surface of
  ``GL02_SCOUT_SURFACE``, and the walker's reviewed limb-state
  functions — the same sites GL02's allowlist documents). An
  undeclared origin is a single-precision value silently promoted
  into the Neumaier accumulators.
* **GL09 — host-interop census.** ``pure_callback`` / ``io_callback``
  / ``debug_callback`` / ``device_put`` primitives in any traced
  engine program are violations, period: GL03's BFS sees only source
  reachability — this sees what tracing actually captured inside the
  program.
* **GL10 — compile-once-by-construction.** Each program is traced
  TWICE with different non-static operand *values* (same
  shapes/dtypes) and the jaxpr hashes must be equal. A value
  accidentally consumed as a static (or baked through a closure)
  shows up as a differing literal — caught here, before it shows up
  as ``ppls_recompiles_total`` in production. The dynamic twin of the
  ``compile_once_guard`` fixture, with zero execution.

Trace REUSE: :func:`collect_traces` traces each program once per seed
and GL07/GL08/GL09 share seed 0's jaxpr while GL10 compares both — one
trace pass serves all four rules, which is what keeps the ci.sh
deep-lint step inside its wall budget. Violations share the AST tier's
line-free ``CODE:path:symbol`` keys and the baseline workflow.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import sys
from typing import Dict, Iterator, List, Optional, Tuple

from tools.graftlint.core import Violation

# seeds for the two value-varied traces (GL10); census rules read the
# first trace only
TRACE_SEEDS = (0, 1)

COLLECTIVE_PRIMS = frozenset({
    "psum", "all_gather", "ppermute", "pmax", "pmin", "pmean",
    "psum_scatter", "reduce_scatter", "all_to_all", "axis_index",
})
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "device_put",
})

# ---------------------------------------------------------------------------
# declared models (reviewed, like GL02_SCOUT_SURFACE — not baselines)
# ---------------------------------------------------------------------------

# GL07: the collective census each dd program is ALLOWED to trace to,
# with the reconciliation story against the device-counted ``crounds``
# model. Counts are exact for the committed probe configurations on
# this container's jax; a new collective (count above the model) fails
# the deep lint, a removed one reports the model entry stale so this
# table shrinks with the code. Targets absent from this table must
# census EMPTY (the single-chip engines pay no collectives at all).
GL07_CROUNDS_MODEL: Dict[str, Dict[str, object]] = {
    "sharded_walker.dd_refill": {
        "collectives": {"psum": 9, "all_gather": 11, "axis_index": 2},
        "reason": (
            "refill-mode reconciliation: the 5 collective-breed-branch "
            "collectives (loop-guard psum, prev-count psum, and the "
            "re-shard's size psum + 5 all_gathers) are counted by "
            "crounds += out.iters per breed round; the phase "
            "reshard's 6 stratified-deal all_gathers + deal psum are "
            "counted by crounds += did per taken reshard; the "
            "remaining psums are REPLICATED PREDICATES (cycle-loop "
            "guard, breed-dispatch occupancy, local-breed/balance/"
            "final overflow) — scalar lockstep decisions, not data "
            "rounds, deliberately outside the crounds claim. "
            "axis_index is deal-index math, no communication."),
    },
    "cluster.worker_dd_stream": {
        "collectives": {"psum": 10, "all_gather": 11,
                        "axis_index": 2},
        "reason": (
            "round 18, the DISTRIBUTED dd program: the phase program "
            "a cluster worker runs (build_dd_walker_run with the "
            "admit window armed) over its LOCAL mesh. Same census as "
            "sharded_walker.dd_refill plus ONE psum — the admission "
            "path's replicated offered-load occupancy predicate "
            "(phase_reshard folds admitted seeds into its decision). "
            "This entry PINS that cluster collectives stay host-"
            "local by construction: cross-process exchange is the "
            "coordinator socket boundary, never a compiled "
            "collective (the CPU backend has none, and a TPU pod "
            "must opt in deliberately)."),
    },
    "sharded_walker.dd_legacy": {
        "collectives": {"psum": 5, "all_gather": 5, "axis_index": 1},
        "reason": (
            "legacy-mode reconciliation: the collective breed chain's "
            "per-round re-shard (size psum + 5 all_gathers, loop-"
            "guard + prev-count psums) is counted by crounds += "
            "out.iters; the cycle-loop guard and final overflow "
            "psums are replicated predicates. No phase reshard in "
            "this mode — its crounds arm is refill-only."),
    },
}

# GL08: the declared f32→f64 origin surface. Everything here is a
# reviewed, deliberate promotion of exact f32 LIMBS into f64 (the ds
# double-single representation reassembling, the pow2 exact scale, the
# scout surface's confirm hand-off) — the same sites GL02's allowlist
# and scout-surface declaration document at the AST level. An f32→f64
# convert originating anywhere else is a single-precision value
# flowing into the f64 credit path. Symbols "*" covers the module.
GL08_DTYPE_SURFACE: Dict[str, Dict[str, object]] = {
    "ops/ds.py": {
        "symbols": ("*",),
        "reason": "the fenced XLA ds module: (hi, lo) f32 limb pairs "
                  "reassemble to f64 exactly — the representation, "
                  "not a downcast recovery."},
    "ops/ds_kernel.py": {
        "symbols": ("*",),
        "reason": "in-kernel ds arithmetic: limb-pair promotion to "
                  "f64 at credit time is the error-free transform "
                  "the kernel is built on."},
    "ops/pow2.py": {
        "symbols": ("*",),
        "reason": "exact power-of-two scale: the f32 exponent-field "
                  "trick promotes an EXACT small value."},
    "ops/scout_kernel.py": {
        "symbols": ("*",),
        "reason": "the declared GL02 scout surface: scout f32 values "
                  "never credit directly (the confirm pass re-takes "
                  "in full ds), so any promotion here is test-chain "
                  "bookkeeping, reviewed with the surface itself."},
    "parallel/walker.py": {
        "symbols": ("to_ds", "to_ds3", "do_swap", "_run_walk",
                    "_run_walk_kernel_refill", "_bank_and_refill",
                    "_expand_pending"),
        "reason": "the walker's lane-state limb columns: ds (two-f32-"
                  "limb) state folding back into f64 bag/credit "
                  "columns — each of these functions carries a "
                  "GL02 allowlist entry (or sits inside one's "
                  "subtree) documenting the deliberate f32."},
}


@dataclasses.dataclass
class DeepTrace:
    """One engine program's captured traces (shared across GL07-GL10)."""

    name: str                 # probe name, e.g. "sharded_walker.dd_refill"
    path: str                 # repo-relative module path (violation anchor)
    jaxprs: Tuple             # one ClosedJaxpr per TRACE_SEEDS entry
    error: Optional[str] = None   # trace failure (reported by GL10)

    @property
    def short(self) -> str:
        return self.name.split(".", 1)[1] if "." in self.name \
            else self.name


def _ensure_jax_env(n_devices: int = 8):
    """Import jax with the deep tier's environment: CPU platform, x64,
    and a virtual multi-device host for the dd mesh — set BEFORE the
    first jax import when this process owns it (the CLI path), left
    alone when the embedding process (pytest's conftest) already
    configured an equivalent environment."""
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{n_devices}").strip()
    import jax
    jax.config.update("jax_enable_x64", True)
    return jax


def default_probes():
    """The committed trace-target registry: every engine module owns a
    ``deep_trace_probes()`` next to its sizing logic (the probes build
    the REAL jitted programs over tiny operands). Returns
    ``[(name, fn, build_operands, module_path), ...]``."""
    _ensure_jax_env()
    from ppls_tpu.parallel import (bag_engine, device_engine,
                                   sharded_walker, walker)
    from ppls_tpu.runtime import cluster, stream
    paths = {
        bag_engine: "ppls_tpu/parallel/bag_engine.py",
        device_engine: "ppls_tpu/parallel/device_engine.py",
        walker: "ppls_tpu/parallel/walker.py",
        stream: "ppls_tpu/runtime/stream.py",
        sharded_walker: "ppls_tpu/parallel/sharded_walker.py",
        cluster: "ppls_tpu/runtime/cluster.py",
    }
    out = []
    for mod, path in paths.items():
        for name, fn, ops in mod.deep_trace_probes():
            out.append((name, fn, ops, path))
    return out


def collect_traces(probes=None) -> List[DeepTrace]:
    """ONE trace pass per (program, seed), shared by all deep rules.

    A probe that fails to trace is not a crash: it comes back as a
    DeepTrace with ``error`` set, which GL10 reports as a violation
    (an engine program that cannot be traced with value-varied
    operands has almost certainly grown an unhashable/static-operand
    mismatch — exactly the drift this tier exists to catch)."""
    jax = _ensure_jax_env()
    if probes is None:
        probes = default_probes()
    out = []
    for name, fn, ops, path in probes:
        try:
            jaxprs = []
            for seed in TRACE_SEEDS:
                # the trace path caches on (function identity, avals):
                # without a cache clear the second seed would be handed
                # the FIRST trace back and a closure-baked value (the
                # exact GL10 failure mode) would be invisible
                jax.clear_caches()
                jaxprs.append(jax.make_jaxpr(fn)(*ops(seed)))
            jaxprs = tuple(jaxprs)
            out.append(DeepTrace(name=name, path=path, jaxprs=jaxprs))
        except Exception as e:     # noqa: BLE001 — reported, not raised
            out.append(DeepTrace(name=name, path=path, jaxprs=(),
                                 error=f"{type(e).__name__}: {e}"))
    return out


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _sub_jaxprs(v) -> Iterator:
    import jax.core as jc
    vals = v if isinstance(v, (list, tuple)) else [v]
    for x in vals:
        if isinstance(x, jc.ClosedJaxpr):
            yield x.jaxpr
        elif isinstance(x, jc.Jaxpr):
            yield x


def iter_eqns(jaxpr) -> Iterator:
    """Every eqn of ``jaxpr`` and (recursively) of every sub-jaxpr in
    its eqn params — pjit bodies, while cond/body, cond branches,
    shard_map bodies, pallas kernels: the whole captured program."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _eqn_origin(eqn) -> Tuple[str, str, int]:
    """(repo-relative-ish file, function, line) of the user frame that
    emitted ``eqn``; ("?", "?", 0) when source info is unavailable."""
    try:
        from jax._src import source_info_util as siu
        fr = siu.user_frame(eqn.source_info)
        if fr is None:
            return "?", "?", 0
        fname = fr.file_name.replace(os.sep, "/")
        i = fname.rfind("/ppls_tpu/")
        rel = fname[i + 1:] if i >= 0 else os.path.basename(fname)
        return rel, fr.function_name, int(fr.start_line
                                          if hasattr(fr, "start_line")
                                          else getattr(fr, "line_num",
                                                       0))
    except Exception:   # noqa: BLE001 — origin is best-effort display
        return "?", "?", 0


def _census(jaxpr, prims) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        p = eqn.primitive.name
        if p in prims:
            out[p] = out.get(p, 0) + 1
    return out


# ---------------------------------------------------------------------------
# GL07 — collective census vs the crounds model
# ---------------------------------------------------------------------------

def rule_gl07(traces: List[DeepTrace],
              model: Optional[Dict] = None) -> Iterator[Violation]:
    model = GL07_CROUNDS_MODEL if model is None else model
    for tr in traces:
        if tr.error:
            continue
        expect = dict(model.get(tr.name, {}).get("collectives", {}))
        got = _census(tr.jaxprs[0].jaxpr, COLLECTIVE_PRIMS)
        for prim in sorted(set(expect) | set(got)):
            g, e = got.get(prim, 0), expect.get(prim, 0)
            if g > e:
                yield Violation(
                    code="GL07", path=tr.path, line=1,
                    symbol=f"{tr.short}:{prim}",
                    message=(
                        f"traced program {tr.name} contains {g} "
                        f"{prim!r} primitive(s), the crounds model "
                        f"declares {e}: an UNCOUNTED collective "
                        f"reached the compiled program (GL04 cannot "
                        f"see through shard_map/cond bodies — this "
                        f"census can). Count it at a crounds "
                        f"boundary and update GL07_CROUNDS_MODEL "
                        f"with the reconciliation, or remove it."))
            elif g < e:
                yield Violation(
                    code="GL07", path=tr.path, line=1,
                    symbol=f"{tr.short}:{prim}:stale-model",
                    message=(
                        f"crounds model declares {e} {prim!r} "
                        f"primitive(s) for {tr.name} but the traced "
                        f"program contains {g}: the model entry is "
                        f"STALE — shrink it to match the program "
                        f"(the census table only shrinks, like the "
                        f"baseline)."))


# ---------------------------------------------------------------------------
# GL08 — f32→f64 dtype-flow audit
# ---------------------------------------------------------------------------

def _surface_covers(surface: Dict, origin_file: str,
                    origin_fn: str) -> bool:
    for suffix, entry in surface.items():
        if origin_file.endswith(suffix):
            syms = entry["symbols"]
            if "*" in syms or origin_fn in syms:
                return True
    return False


def rule_gl08(traces: List[DeepTrace],
              surface: Optional[Dict] = None) -> Iterator[Violation]:
    surface = GL08_DTYPE_SURFACE if surface is None else surface
    seen = set()
    for tr in traces:
        if tr.error:
            continue
        for eqn in iter_eqns(tr.jaxprs[0].jaxpr):
            if eqn.primitive.name != "convert_element_type":
                continue
            try:
                src = str(eqn.invars[0].aval.dtype)
            except Exception:   # noqa: BLE001 — literal invars
                continue
            dst = str(eqn.params.get("new_dtype"))
            if src != "float32" or dst != "float64":
                continue
            ofile, ofn, oline = _eqn_origin(eqn)
            if _surface_covers(surface, ofile, ofn):
                continue
            key = (ofile, ofn)
            if key in seen:
                continue
            seen.add(key)
            yield Violation(
                code="GL08", path=ofile if ofile != "?" else tr.path,
                line=oline or 1,
                symbol=f"{ofn}:f32-to-f64",
                message=(
                    f"f32→f64 convert_element_type originating in "
                    f"{ofn} ({ofile}) reached the traced program "
                    f"{tr.name}: a single-precision value is being "
                    f"promoted into the f64 credit path outside the "
                    f"declared dtype surface (ds limbs / scout "
                    f"surface). Route it through the ds "
                    f"representation, or declare the origin in "
                    f"GL08_DTYPE_SURFACE with a reviewed reason."))


# ---------------------------------------------------------------------------
# GL09 — host-interop census
# ---------------------------------------------------------------------------

def rule_gl09(traces: List[DeepTrace]) -> Iterator[Violation]:
    for tr in traces:
        if tr.error:
            continue
        got = _census(tr.jaxprs[0].jaxpr, CALLBACK_PRIMS)
        for prim, n in sorted(got.items()):
            yield Violation(
                code="GL09", path=tr.path, line=1,
                symbol=f"{tr.short}:{prim}",
                message=(
                    f"traced program {tr.name} contains {n} {prim!r} "
                    f"primitive(s): host interop inside an engine "
                    f"program stalls every cycle on a device→host "
                    f"round-trip (and a debug callback left behind "
                    f"fires per execution forever). GL03's source "
                    f"BFS cannot see wrapped callbacks — tracing "
                    f"can. Remove it, or move the interop to the "
                    f"host boundary."))


# ---------------------------------------------------------------------------
# GL10 — compile-once-by-construction (jaxpr-hash stability)
# ---------------------------------------------------------------------------

def _jaxpr_hash(closed) -> str:
    return hashlib.sha256(str(closed).encode()).hexdigest()[:16]


def rule_gl10(traces: List[DeepTrace]) -> Iterator[Violation]:
    for tr in traces:
        if tr.error:
            yield Violation(
                code="GL10", path=tr.path, line=1,
                symbol=f"{tr.short}:trace-error",
                message=(
                    f"engine program {tr.name} failed to trace with "
                    f"value-varied operands: {tr.error} — an "
                    f"unhashable static / operand mismatch has "
                    f"drifted into the entry point."))
            continue
        hashes = [_jaxpr_hash(j) for j in tr.jaxprs]
        if len(set(hashes)) > 1:
            yield Violation(
                code="GL10", path=tr.path, line=1,
                symbol=f"{tr.short}:jaxpr-hash",
                message=(
                    f"engine program {tr.name} traces to DIFFERENT "
                    f"jaxprs for different non-static operand values "
                    f"({' vs '.join(hashes)}): an operand value is "
                    f"being baked into the program (accidental "
                    f"static / closure capture) — in production this "
                    f"is one recompile per distinct value "
                    f"(ppls_recompiles_total). Make the value a "
                    f"traced operand."))


DEEP_RULES = (rule_gl07, rule_gl08, rule_gl09, rule_gl10)
DEEP_CODES = ("GL07", "GL08", "GL09", "GL10")


def run_deep(probes=None, traces: Optional[List[DeepTrace]] = None
             ) -> List[Violation]:
    """Run the semantic tier: one shared trace pass, all four rules.

    Pass ``traces`` to reuse an existing :func:`collect_traces` result
    (the test suite caches one per session; ci.sh gets the reuse for
    free inside a single CLI invocation)."""
    if traces is None:
        traces = collect_traces(probes)
    out: List[Violation] = []
    for rule in DEEP_RULES:
        out.extend(rule(traces))
    out.sort(key=lambda v: (v.path, v.line, v.code, v.symbol))
    return out
