"""GL01-GL05: the project-specific rule set.

Each rule is ``rule(modules) -> Iterable[Violation]`` over the parsed
package (see core.py).  Rules are deliberately *structural* — they key
off the repo's own conventions (carry NamedTuples, the
``save_family_checkpoint`` identity surface, the ``crounds`` counter,
``static_argnames`` declarations) rather than generic JAX style, which
is what makes a committed baseline of a handful of reviewed sites
possible instead of hundreds of generic warnings.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint.core import LintModule, Violation

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def iter_functions(tree: ast.Module
                   ) -> Iterator[Tuple[str, ast.FunctionDef]]:
    """Top-level functions and class methods as (qualname, node).
    Nested closures stay inside their parent's subtree (a function's
    "scope" for every rule below is its whole subtree)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); '' if not dotted."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _jit_statics(fn: ast.FunctionDef) -> Optional[Tuple[str, ...]]:
    """If ``fn`` is decorated as a jitted entry, return its declared
    static_argnames (possibly empty); None when not jitted.

    Recognized forms: ``@jax.jit``, ``@jit``, and
    ``@[functools.]partial(jax.jit, static_argnames=(...))``.
    """
    for dec in fn.decorator_list:
        d = _dotted(dec)
        if d in ("jax.jit", "jit"):
            return ()
        if isinstance(dec, ast.Call):
            head = _dotted(dec.func)
            if head not in ("functools.partial", "partial"):
                continue
            if not dec.args or _dotted(dec.args[0]) not in ("jax.jit",
                                                            "jit"):
                continue
            for kw in dec.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    return tuple(_const_strings(kw.value))
            return ()
    return None


def _const_strings(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_const_strings(e))
        return out
    return []


def _param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs]
            + ([a.vararg.arg] if a.vararg else [])
            + ([a.kwarg.arg] if a.kwarg else []))


def _docstring_consts(node: ast.AST) -> Set[int]:
    """ids of the Constant nodes that are docstrings anywhere under
    ``node`` — prose must not count as code-level accounting: a
    docstring *mentioning* a counter or a field name is not the same
    as persisting/incrementing it."""
    out: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Module)):
            body = n.body
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def _string_surface(node: ast.AST) -> Set[str]:
    """Every way a field name can be 'mentioned' by snapshot code:
    string constants (dict keys, tuple-of-names tables, np.savez keys)
    and keyword-argument names (``dict(tasks=0)``, ``overflow=ovf``).
    Docstrings are excluded — prose is not persistence."""
    docs = _docstring_consts(node)
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and id(n) not in docs:
            out.add(n.value)
        elif isinstance(n, ast.keyword) and n.arg:
            out.add(n.arg)
    return out


def _called_names(node: ast.AST) -> Set[str]:
    """Simple callee names (both ``f(...)`` and ``mod.f(...)``)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Name):
                out.add(n.func.id)
            elif isinstance(n.func, ast.Attribute):
                out.add(n.func.attr)
    return out


# ---------------------------------------------------------------------------
# GL01 — snapshot-identity completeness
# ---------------------------------------------------------------------------

_CHECKPOINT_APIS = {
    "save_family_checkpoint", "load_family_checkpoint",
    "save_checkpoint", "load_checkpoint",
    "_family_identity", "_family_ckpt_identity", "_stream_identity",
    "_dd_ckpt_identity",
}
_SNAPSHOT_NAME_RE = re.compile(r"identity|checkpoint|snapshot|resume",
                               re.IGNORECASE)

# Spelling bridges between carry fields and their on-disk names.  Kept
# deliberately tiny: a rename that breaks one of these should be FELT.
_GL01_ALIASES: Dict[str, Set[str]] = {
    "bag": {"bag_cols"},
    "bag_l": {"l"}, "bag_r": {"r"}, "bag_th": {"th"},
    "bag_meta": {"meta"},
    "maxd": {"max_depth"},
}


def _carry_classes(mod: LintModule
                   ) -> List[Tuple[ast.ClassDef, List[Tuple[str, int]]]]:
    """NamedTuple/dataclass definitions named ``*Carry`` with their
    (field, line) lists."""
    out = []
    for node in mod.tree.body:
        if not (isinstance(node, ast.ClassDef)
                and node.name.endswith("Carry")):
            continue
        is_nt = any(_dotted(b).split(".")[-1] == "NamedTuple"
                    for b in node.bases)
        is_dc = any(_dotted(d).split(".")[-1] == "dataclass"
                    or (isinstance(d, ast.Call)
                        and _dotted(d.func).split(".")[-1] == "dataclass")
                    for d in node.decorator_list)
        if not (is_nt or is_dc):
            continue
        fields = [(s.target.id, s.lineno) for s in node.body
                  if isinstance(s, ast.AnnAssign)
                  and isinstance(s.target, ast.Name)]
        out.append((node, fields))
    return out


def rule_gl01(modules: List[LintModule]) -> Iterator[Violation]:
    """GL01: every field of every walker/stream/dd carry container must
    be represented on the checkpoint identity surface.

    The PR-2 near-miss this encodes: ``refill_slots`` changed the
    meaning of the persisted state but was not part of the snapshot
    identity, so a refill snapshot could silently resume a legacy run.
    Mechanically: for each ``*Carry`` NamedTuple/dataclass that is
    referenced by the module's snapshot code (directly, or by a
    function the snapshot code calls — the run entry whose result gets
    persisted), every field name must appear among the string
    constants / keyword names of the snapshot functions themselves (or
    of ``runtime/checkpoint.py``), modulo the tiny documented alias
    map.  A field the snapshot surface never mentions is state the
    resume path cannot restore."""
    global_surface: Set[str] = set()
    for mod in modules:
        if mod.path.endswith("runtime/checkpoint.py"):
            global_surface |= _string_surface(mod.tree)
    for mod in modules:
        carries = _carry_classes(mod)
        if not carries:
            continue
        funcs = dict(iter_functions(mod.tree))
        contributing = {
            qn: fn for qn, fn in funcs.items()
            if _SNAPSHOT_NAME_RE.search(qn)
            or (_called_names(fn) & _CHECKPOINT_APIS)
        }
        if not contributing:
            continue
        surface = set(global_surface)
        referencing: List[ast.AST] = []
        one_hop: Set[str] = set()
        for fn in contributing.values():
            surface |= _string_surface(fn)
            referencing.append(fn)
            one_hop |= _called_names(fn)
        for qn, fn in funcs.items():
            if qn in one_hop and qn not in contributing:
                referencing.append(fn)
        in_scope_names: Set[str] = set()
        for node in referencing:
            for n in ast.walk(node):
                if isinstance(n, ast.Name):
                    in_scope_names.add(n.id)
        for cls, fields in carries:
            if cls.name not in in_scope_names:
                continue        # kernel-internal carry, never persisted
            for field, line in fields:
                names = {field} | _GL01_ALIASES.get(field, set())
                if names & surface:
                    continue
                yield Violation(
                    code="GL01", path=mod.path, line=line,
                    symbol=f"{cls.name}.{field}",
                    message=(
                        f"carry field {cls.name}.{field} is absent from "
                        f"the snapshot identity surface: no snapshot/"
                        f"identity function in this module mentions "
                        f"{sorted(names)} — a resumed run cannot "
                        f"restore it. Persist it (bag_cols/totals/"
                        f"identity), or allowlist with the reason it "
                        f"is derived state."))


# ---------------------------------------------------------------------------
# GL02 — f64 dtype discipline
# ---------------------------------------------------------------------------

# Creation calls whose dtype defaults are config-dependent (f32 without
# jax_enable_x64).  jnp.array/asarray are only flagged for literal
# payloads: wrapping an existing traced array inherits its dtype.
_GL02_CREATORS = {"zeros", "ones", "empty", "full", "arange",
                  "linspace"}
_GL02_DTYPE_POSITION = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                        "array": 1, "asarray": 1}
# The ds (double-double) representation IS a pair of f32 limbs: its
# kernels are f32 by construction, not by accident.
_GL02_F32_EXEMPT = re.compile(r"ops/(ds_kernel|pow2|ds)\.py$")

# Round-12 DECLARED SCOUT-DTYPE SURFACE: the mixed-precision scouting
# pass is DELIBERATELY f32 — but only where declared. Each entry names
# a module (path suffix), the symbols (function qualnames, or "*" for
# the whole module) allowed to reference f32, and the REVIEWED reason.
# This is a declaration, not a baseline: f32 outside the listed
# (module, symbol) pairs still fails GL02, and additions here are a
# code-reviewed API change, never a silent baseline growth
# (tests/test_graftlint.py pins both directions).
GL02_SCOUT_SURFACE = {
    "ops/scout_kernel.py": {
        "*": "the declared f32 scout surface itself: a single-precision "
             "ds-API twin evaluated ONLY by the walker's scout pass — "
             "f32 is the module's entire purpose, and every scout "
             "decision it feeds is either decisively-split (guard band) "
             "or re-taken in full ds by the confirm pass.",
    },
}


def _scout_surface_entry(path: str, qn: str):
    """The declared scout-surface reason covering (module, symbol), or
    None when the pair is not declared."""
    for suffix, symbols in GL02_SCOUT_SURFACE.items():
        if path.endswith(suffix):
            if "*" in symbols:
                return symbols["*"]
            if qn in symbols:
                return symbols[qn]
            # bare function name of a ClassName.method qualname
            if qn.split(".")[-1] in symbols:
                return symbols[qn.split(".")[-1]]
    return None


def _is_literal_payload(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex, bool))
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literal_payload(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literal_payload(node.operand)
    return False


def rule_gl02(modules: List[LintModule]) -> Iterator[Violation]:
    """GL02: f64 dtype discipline in ``parallel/`` and ``ops/``.

    Flags (a) dtype-less ``jnp.zeros/ones/empty/full/arange/linspace``
    and literal-payload ``jnp.array/asarray`` — their dtype is whatever
    ``jax_enable_x64`` happens to be, i.e. f32 in any embedding that
    forgot the flag, silently downcasting an accumulator path; and
    (b) ``float32`` references outside the ds-limb modules (ds kernels
    are f32 *by representation*; everywhere else f32 in a numeric path
    is a downcast hazard).  Literal arithmetic (``0.5 * x``) is NOT
    flagged: under weak typing literals adopt the array operand's
    dtype, so the hazard is creation, not arithmetic.

    Round 12: the DECLARED scout-dtype surface (``GL02_SCOUT_SURFACE``
    — module + symbol list, per-entry reviewed reason) carves out the
    mixed-precision scouting pass from the float32 check only; the
    dtype-less-creation check still applies inside it, and f32 outside
    the declared pairs still fails."""
    for mod in modules:
        if "/parallel/" not in "/" + mod.path \
                and "/ops/" not in "/" + mod.path:
            continue
        f32_hits: Dict[str, Tuple[int, int]] = {}
        for qn, fn in iter_functions(mod.tree):
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    head = _dotted(n.func)
                    parts = head.split(".")
                    if len(parts) == 2 and parts[0] in ("jnp", "jax_np"):
                        name = parts[1]
                        has_dtype = any(kw.arg == "dtype"
                                        for kw in n.keywords)
                        pos = _GL02_DTYPE_POSITION.get(name)
                        if pos is not None and len(n.args) > pos:
                            has_dtype = True
                        if name in _GL02_CREATORS and not has_dtype \
                                and name not in ("array", "asarray"):
                            yield Violation(
                                code="GL02", path=mod.path,
                                line=n.lineno,
                                symbol=f"{qn}:dtype-less-{name}",
                                message=(
                                    f"jnp.{name}(...) without an "
                                    f"explicit dtype in a numeric "
                                    f"path: the result is f32 unless "
                                    f"jax_enable_x64 is set — pass "
                                    f"dtype=jnp.float64 (or the "
                                    f"intended integer dtype)."))
                        elif name in ("array", "asarray") \
                                and not has_dtype and n.args \
                                and _is_literal_payload(n.args[0]):
                            yield Violation(
                                code="GL02", path=mod.path,
                                line=n.lineno,
                                symbol=f"{qn}:dtype-less-{name}",
                                message=(
                                    f"jnp.{name}(<literal>) without "
                                    f"dtype: literal payloads default "
                                    f"to the x64-flag dtype — make "
                                    f"the f64 (or integer) intent "
                                    f"explicit."))
                if not _GL02_F32_EXEMPT.search(mod.path) \
                        and _scout_surface_entry(mod.path, qn) is None:
                    is_f32 = (
                        (isinstance(n, ast.Attribute)
                         and n.attr == "float32")
                        or (isinstance(n, ast.Constant)
                            and n.value == "float32"))
                    if is_f32 and qn not in f32_hits:
                        f32_hits[qn] = (n.lineno, 1)
                    elif is_f32:
                        line, cnt = f32_hits[qn]
                        f32_hits[qn] = (line, cnt + 1)
        for qn, (line, cnt) in f32_hits.items():
            yield Violation(
                code="GL02", path=mod.path, line=line,
                symbol=f"{qn}:float32",
                message=(
                    f"{cnt} float32 reference(s) in {qn}: f32 in a "
                    f"numeric path silently downcasts the f64 "
                    f"accumulator chain. If the f32 is deliberate "
                    f"(ds limbs, lane-state packing), allowlist this "
                    f"function with that reason."))


# ---------------------------------------------------------------------------
# GL03 — host syncs reachable from jitted roots
# ---------------------------------------------------------------------------

_HOST_SYNC_ATTRS = {"device_get", "device_put", "block_until_ready",
                    "item", "tolist"}
_NP_ALIASES = {"np", "numpy", "onp"}


def _jit_roots(mod: LintModule
               ) -> List[Tuple[str, ast.FunctionDef, Tuple[str, ...]]]:
    """Jitted entries of a module: decorated defs, plus local function
    names passed (possibly through wrappers like ``shard_map_compat``)
    into a ``jax.jit(...)`` call — the builder pattern the sharded
    engines use."""
    roots = []
    for qn, fn in iter_functions(mod.tree):
        statics = _jit_statics(fn)
        if statics is not None:
            roots.append((qn, fn, statics))
    local_defs: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(mod.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs.setdefault(n.name, n)
    seen = {qn for qn, _, _ in roots}

    def names_in(node):
        for x in ast.walk(node):
            if isinstance(x, ast.Name):
                yield x.id

    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and _dotted(n.func) in ("jax.jit",
                                                           "jit"):
            for arg in n.args[:1]:
                for name in names_in(arg):
                    fn = local_defs.get(name)
                    if fn is not None and name not in seen:
                        seen.add(name)
                        statics = tuple(
                            s for kw in n.keywords
                            if kw.arg in ("static_argnames",
                                          "static_argnums")
                            for s in _const_strings(kw.value))
                        roots.append((name, fn, statics))
    return roots


def _build_call_index(modules: List[LintModule]
                      ) -> Dict[str, Dict[str, ast.FunctionDef]]:
    """modkey -> {top-level function/method name -> node}."""
    return {m.modkey: dict(iter_functions(m.tree)) for m in modules}


def _resolve_callee(mod: LintModule, call: ast.Call,
                    index: Dict[str, Dict[str, ast.FunctionDef]]
                    ) -> Optional[Tuple[str, str]]:
    """(modkey, qualname) of an intra-package callee, else None."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in index.get(mod.modkey, {}):
            return mod.modkey, f.id
        imp = mod.name_imports.get(f.id)
        if imp is not None:
            base, orig = imp
            if orig in index.get(base, {}):
                return base, orig
        return None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        target_mod = mod.module_aliases.get(f.value.id)
        if target_mod is not None and f.attr in index.get(target_mod,
                                                          {}):
            return target_mod, f.attr
    return None


def _static_name_pool(modules: List[LintModule]) -> Set[str]:
    """Union of every declared static argname in the package: a name
    in this pool passed to ``int()`` inside a traced body is a
    trace-time config coercion, not a host sync."""
    pool: Set[str] = set()
    for mod in modules:
        for _, fn, statics in _jit_roots(mod):
            pool.update(statics)
    return pool


def _arg_is_trace_safe(node: ast.AST, static_pool: Set[str]) -> bool:
    """int()/float() args that are NOT host syncs: constants, shape
    reads (static under tracing), and static-config names."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim",
                                                       "dtype"):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return True
    names = [n.id for n in ast.walk(node) if isinstance(n, ast.Name)]
    if names and all(nm in static_pool for nm in names):
        return True
    return not names    # pure-constant expression


def _jit_reachable(modules: List[LintModule]):
    """BFS the intra-package call graph from every jitted root.

    Returns ``(visited, lookup)``: the reachable ``(modkey, qualname)``
    set and a resolver to each function's AST node. Shared by GL03
    (host syncs) and GL06 (telemetry publishes) — both defend the same
    boundary: code reachable from a jitted root runs under tracing.
    """
    index = _build_call_index(modules)
    mod_by_key = {m.modkey: m for m in modules}
    # nested defs too: builder-pattern roots (jax.jit(wrap(body)) where
    # body is a closure) are not top-level functions
    all_defs: Dict[str, Dict[str, ast.FunctionDef]] = {}
    for m in modules:
        d: Dict[str, ast.FunctionDef] = {}
        for n in ast.walk(m.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                d.setdefault(n.name, n)
        all_defs[m.modkey] = d

    def _lookup(modkey: str, qn: str) -> Optional[ast.FunctionDef]:
        return index[modkey].get(qn) or all_defs[modkey].get(qn)
    # BFS the reachable set
    queue: List[Tuple[str, str]] = []
    for mod in modules:
        for qn, fn, _ in _jit_roots(mod):
            queue.append((mod.modkey, qn))
    visited: Set[Tuple[str, str]] = set()
    while queue:
        key = queue.pop()
        if key in visited:
            continue
        visited.add(key)
        modkey, qn = key
        mod = mod_by_key[modkey]
        fn = _lookup(modkey, qn)
        if fn is None:
            continue
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                callee = _resolve_callee(mod, n, index)
                if callee is not None and callee not in visited:
                    queue.append(callee)
    return visited, _lookup


def rule_gl03(modules: List[LintModule]) -> Iterator[Violation]:
    """GL03: host synchronization inside the traced hot path.

    Walks the intra-package call graph from every jitted root (the
    ``@jax.jit`` entries of walker.py/stream.py and the
    ``jax.jit(shard_map_compat(...))`` builders of the sharded
    engines) and flags, in any reachable function body:
    ``jax.device_get/device_put``, ``.block_until_ready()``,
    ``.item()/.tolist()``, ``np.*`` calls on non-constant arguments,
    and ``int()/float()/bool()`` coercions of traced values.  Under
    ``jit`` these either fail at trace time in the best case or —
    with AOT-style retracing — force a device round-trip per cycle in
    the hot loop, which is exactly the failure mode the device-counted
    ``crounds``/phase claims exist to rule out."""
    mod_by_key = {m.modkey: m for m in modules}
    static_pool = _static_name_pool(modules)
    visited, _lookup = _jit_reachable(modules)
    for modkey, qn in sorted(visited):
        mod = mod_by_key[modkey]
        fn = _lookup(modkey, qn)
        if fn is None:
            continue
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            head = _dotted(n.func)
            parts = head.split(".")
            sync = None
            if head in ("jax.device_get", "jax.device_put"):
                sync = head
            elif isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("block_until_ready", "item",
                                        "tolist"):
                sync = f".{n.func.attr}()"
            elif len(parts) == 2 and parts[0] in _NP_ALIASES:
                # np.float32(eps) on a static config name is trace-time
                # constant construction, not a sync
                if any(not _arg_is_trace_safe(a, static_pool)
                       for a in n.args):
                    sync = head
            elif isinstance(n.func, ast.Name) \
                    and n.func.id in ("int", "float", "bool") \
                    and n.args \
                    and not _arg_is_trace_safe(n.args[0], static_pool):
                sync = f"{n.func.id}()"
            if sync is None:
                continue
            yield Violation(
                code="GL03", path=mod.path, line=n.lineno,
                symbol=f"{qn}:{sync}",
                message=(
                    f"{sync} inside {qn}, which is reachable from a "
                    f"jitted root: a host sync in the traced hot path "
                    f"either breaks tracing or forces a device "
                    f"round-trip per cycle. Hoist it to the host "
                    f"driver, or allowlist with the reason it only "
                    f"runs at trace time."))


# ---------------------------------------------------------------------------
# GL04 — uncounted collectives in the dd engine
# ---------------------------------------------------------------------------

_COLLECTIVES = {"psum", "all_gather", "ppermute", "pmax", "pmin",
                "pmean", "psum_scatter", "all_to_all"}
_GL04_SCOPE = re.compile(r"(sharded_walker|mesh)\.py$")


def rule_gl04(modules: List[LintModule]) -> Iterator[Violation]:
    """GL04: every collective in the dd engine must be paired with
    ``crounds`` accounting.

    The dd walker's headline claim (2.4-3.0 collective rounds/cycle vs
    legacy's 7-10.5) is backed by the device-counted ``crounds``
    counter; a collective added without touching ``crounds`` silently
    falsifies that accounting.  Mechanically: any top-level function in
    ``sharded_walker.py``/``mesh.py`` whose subtree performs a
    ``lax.psum/all_gather/ppermute/...`` must also reference
    ``crounds`` somewhere in the same subtree (increment, carry field,
    or an explicit pass-through).  Primitives whose collectives are
    counted by their caller belong in the allowlist with that reason.
    """
    for mod in modules:
        if not _GL04_SCOPE.search(mod.path):
            continue
        for qn, fn in iter_functions(mod.tree):
            hits: List[ast.Call] = []
            counted = False
            docs = _docstring_consts(fn)
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    head = _dotted(n.func)
                    parts = head.split(".")
                    if (parts[-1] in _COLLECTIVES
                            and (len(parts) == 1
                                 or parts[-2] in ("lax", "jax"))):
                        hits.append(n)
                if isinstance(n, ast.Name) and "crounds" in n.id:
                    counted = True
                elif isinstance(n, ast.Attribute) \
                        and "crounds" in n.attr:
                    counted = True
                elif isinstance(n, ast.keyword) and n.arg \
                        and "crounds" in n.arg:
                    counted = True
                elif isinstance(n, ast.Constant) \
                        and isinstance(n.value, str) \
                        and "crounds" in n.value \
                        and id(n) not in docs:
                    # a docstring saying "crounds is handled by the
                    # caller" is prose — the allowlist (with a
                    # reviewable reason) is the only sanctioned
                    # caller-counts-it escape hatch
                    counted = True
            if hits and not counted:
                yield Violation(
                    code="GL04", path=mod.path, line=hits[0].lineno,
                    symbol=qn,
                    message=(
                        f"{qn} performs {len(hits)} collective(s) "
                        f"(lax.psum/all_gather/...) but never touches "
                        f"the crounds counter: the device-counted "
                        f"collective-round claims no longer cover "
                        f"this path. Increment crounds at the "
                        f"boundary, or allowlist with the reason the "
                        f"caller counts it."))


# ---------------------------------------------------------------------------
# GL05 — static-arg drift
# ---------------------------------------------------------------------------

_HASHABLE_ANNOTATIONS = {"int", "float", "bool", "str", "Callable",
                         "Rule"}


def _is_config_param(arg: ast.arg, default: Optional[ast.AST]) -> bool:
    ann = arg.annotation
    if ann is not None:
        if _dotted(ann).split(".")[-1] in _HASHABLE_ANNOTATIONS:
            return True
        # Callable[..., X] — subscripted form
        if isinstance(ann, ast.Subscript) \
                and _dotted(ann.value).split(".")[-1] == "Callable":
            return True
    if default is not None and isinstance(default, ast.Constant) \
            and isinstance(default.value, (int, float, bool, str)) \
            and default.value is not None:
        return True
    return False


def rule_gl05(modules: List[LintModule]) -> Iterator[Violation]:
    """GL05: static-arg drift on jitted entries.

    Three drifts, all of which have bitten jitted-config code before:
    (a) a name in ``static_argnames`` that is no longer a parameter —
    silently ignored by jax, so the "static" silently became traced
    after a rename; (b) a hashable config parameter (Callable / int /
    float / bool / str / Rule annotation, or scalar default) that is
    NOT declared static — Callables fail at trace time, scalars trace
    into the program and change numerics-by-config into
    numerics-by-input; (c) a call site feeding a declared static from
    an enclosing loop variable — one recompile per iteration, the
    recompile-storm shape."""
    # (modkey, bare name) -> statics, so same-named jitted functions in
    # different modules don't shadow each other, and call sites resolve
    # through the import bindings instead of by bare-name guesswork
    jit_sigs: Dict[Tuple[str, str], Tuple[str, ...]] = {}
    for mod in modules:
        for qn, fn, statics in _jit_roots(mod):
            jit_sigs[(mod.modkey, qn.split(".")[-1])] = statics
            params = set(_param_names(fn))
            for s in statics:
                if s not in params:
                    yield Violation(
                        code="GL05", path=mod.path, line=fn.lineno,
                        symbol=f"{qn}:{s}:not-a-param",
                        message=(
                            f"static_argnames entry {s!r} of {qn} is "
                            f"not a parameter: jax ignores unknown "
                            f"names, so after a rename the value is "
                            f"silently traced. Fix the declaration."))
            # hashable config params anywhere in the signature:
            # keyword-only (the dominant convention here) AND annotated
            # / scalar-defaulted positional-or-keyword params — a
            # jitted `def f(x, eps: float = 1e-7)` leaks config into
            # the traced signature just the same
            pos = fn.args.posonlyargs + fn.args.args
            pos_defaults = [None] * (len(pos) - len(fn.args.defaults)) \
                + list(fn.args.defaults)
            candidates = list(zip(pos, pos_defaults)) \
                + list(zip(fn.args.kwonlyargs, fn.args.kw_defaults))
            for arg, default in candidates:
                if arg.arg in statics:
                    continue
                if _is_config_param(arg, default):
                    yield Violation(
                        code="GL05", path=mod.path, line=arg.lineno,
                        symbol=f"{qn}:{arg.arg}:undeclared-static",
                        message=(
                            f"keyword-only config param {arg.arg!r} "
                            f"of jitted {qn} is hashable "
                            f"(annotation/default) but not in "
                            f"static_argnames: a Callable here fails "
                            f"at trace time, a scalar gets traced "
                            f"and varies the compiled program's "
                            f"numerics per call. Declare it static "
                            f"or drop the config flavor."))
    def _callee_statics(mod: LintModule, call: ast.Call
                        ) -> Tuple[Optional[str],
                                   Optional[Tuple[str, ...]]]:
        """(display name, statics) when the call site resolves to a
        known jitted function via this module's bindings; (None, None)
        otherwise — an unresolvable ``obj.method(...)`` must not match
        a jitted function that happens to share the bare name."""
        f = call.func
        if isinstance(f, ast.Name):
            if (mod.modkey, f.id) in jit_sigs:
                return f.id, jit_sigs[(mod.modkey, f.id)]
            imp = mod.name_imports.get(f.id)
            if imp is not None and imp in jit_sigs:
                return f.id, jit_sigs[imp]
        elif isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name):
            target_mod = mod.module_aliases.get(f.value.id)
            if target_mod is not None \
                    and (target_mod, f.attr) in jit_sigs:
                return f.attr, jit_sigs[(target_mod, f.attr)]
        return None, None

    # (c) loop-varying statics at call sites, package-wide
    for mod in modules:

        def scan(node: ast.AST, loop_targets: Set[str], qn: str):
            for child in ast.iter_child_nodes(node):
                targets = loop_targets
                if isinstance(child, ast.For):
                    targets = loop_targets | {
                        n.id for n in ast.walk(child.target)
                        if isinstance(n, ast.Name)}
                elif isinstance(child, (ast.ListComp, ast.SetComp,
                                        ast.GeneratorExp, ast.DictComp)):
                    # a call per comprehension element is the same
                    # recompile storm as a for-statement body
                    targets = loop_targets | {
                        n.id for g in child.generators
                        for n in ast.walk(g.target)
                        if isinstance(n, ast.Name)}
                if isinstance(child, ast.Call):
                    name, statics = _callee_statics(mod, child)
                    if statics:
                        for kw in child.keywords:
                            if kw.arg not in statics:
                                continue
                            used = {n.id for n in ast.walk(kw.value)
                                    if isinstance(n, ast.Name)}
                            bad = used & loop_targets
                            if bad:
                                yield Violation(
                                    code="GL05", path=mod.path,
                                    line=child.lineno,
                                    symbol=(f"{qn}:{name}."
                                            f"{kw.arg}:loop-varying"),
                                    message=(
                                        f"call to jitted {name} "
                                        f"feeds static arg "
                                        f"{kw.arg!r} from loop "
                                        f"variable(s) "
                                        f"{sorted(bad)}: one "
                                        f"recompile per iteration "
                                        f"(recompile storm). Hoist "
                                        f"the value or make the "
                                        f"arg traced."))
                yield from scan(child, targets, qn)

        for qn, fn in iter_functions(mod.tree):
            yield from scan(fn, set(), qn)


# ---------------------------------------------------------------------------
# GL06 — telemetry publishes only at host boundaries
# ---------------------------------------------------------------------------

# The obs-layer publish/emit surface (obs.telemetry / obs.registry /
# obs.spans method names). `.set` is deliberately ABSENT: jax's
# `x.at[i].set(v)` shares the attribute name, and gauges are only
# reachable through the obs-imported handles the name check below
# already covers.
_GL06_API = {"inc", "set_max", "observe", "event", "span",
             "publish_run", "publish_phase", "publish_compile_cache",
             "publish_compile", "publish_chip_balance", "record_phase",
             "stream_counter", "stream_gauge", "emit_event"}


def _imports_obs(mod: LintModule) -> bool:
    """Whether the module binds anything from the obs subpackage."""
    if any(v == "obs" or v.startswith("obs/")
           for v in mod.module_aliases.values()):
        return True
    return any(base == "obs" or base.startswith("obs/")
               for base, _ in mod.name_imports.values())


def rule_gl06(modules: List[LintModule]) -> Iterator[Violation]:
    """GL06: telemetry reads/writes (registry publishes, span/event
    emits) may only occur in boundary-hook functions — never inside a
    function reachable from a jitted root.

    The telemetry layer's contract is "one device fetch per boundary,
    publishes are host dict arithmetic on values the boundary already
    pulled" (obs/__init__.py). A publish that drifts into the traced
    cycle body breaks it two ways at once: the Python side effect
    runs at TRACE time (the registry records one phantom sample per
    compile, not per execution — silently wrong counts), and any
    value it needs forces the GL03 host-sync shape. Mechanically: in
    any function reachable from a jitted root (the GL03 BFS), flag
    (a) calls to names imported from ``obs`` modules, and (b) — in
    modules that import obs — attribute calls spelling an obs API
    method (``.inc``/``.observe``/``.event``/``.span``/
    ``publish_*``/...)."""
    mod_by_key = {m.modkey: m for m in modules}
    visited, _lookup = _jit_reachable(modules)
    for modkey, qn in sorted(visited):
        mod = mod_by_key[modkey]
        fn = _lookup(modkey, qn)
        if fn is None:
            continue
        obs_mod = _imports_obs(mod)
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            hit = None
            f = n.func
            if isinstance(f, ast.Name):
                imp = mod.name_imports.get(f.id)
                if imp is not None and (imp[0] == "obs"
                                        or imp[0].startswith("obs/")):
                    hit = f.id
            elif isinstance(f, ast.Attribute):
                if obs_mod and f.attr in _GL06_API:
                    hit = f.attr
                # obs_module.anything(...) through a module alias
                elif isinstance(f.value, ast.Name):
                    tgt = mod.module_aliases.get(f.value.id)
                    if tgt is not None and (tgt == "obs"
                                            or tgt.startswith("obs/")):
                        hit = f"{f.value.id}.{f.attr}"
            if hit is None:
                continue
            yield Violation(
                code="GL06", path=mod.path, line=n.lineno,
                symbol=f"{qn}:{hit}",
                message=(
                    f"telemetry publish/emit {hit!r} inside {qn}, "
                    f"which is reachable from a jitted root: the "
                    f"side effect fires at trace time (one phantom "
                    f"sample per compile) and its inputs force a "
                    f"host sync. Move the publish to the host "
                    f"boundary hook that already holds the fetched "
                    f"values."))


ALL_RULES = (rule_gl01, rule_gl02, rule_gl03, rule_gl04, rule_gl05,
             rule_gl06)
