"""The graftlint rule set, one module per concern (round 17: the
pre-split ``rules.py`` monolith became this package when the deep tier
landed — shared AST machinery lives in ``_ast.py``, each GL rule keeps
its docstring rationale next to its code, and the public import
surface below is unchanged).

AST tier (this package, always on):

* GL01 snapshot-identity completeness     (``snapshot.py``)
* GL02 f64 dtype discipline               (``dtype.py``)
* GL03 host syncs in the traced hot path  (``hotpath.py``)
* GL04 uncounted collectives, source view (``collectives.py``)
* GL05 static-arg drift                   (``statics.py``)
* GL06 telemetry publishes at boundaries  (``hotpath.py``)
* GL11 lock discipline for shared state   (``locks.py``)

Semantic tier (``tools/graftlint/deep.py``, ``--deep``): GL07-GL10
trace the real jitted engine programs and walk the captured jaxprs —
see that module for the census/model machinery.
"""

from tools.graftlint.rules._ast import (  # noqa: F401
    _arg_is_trace_safe,
    _build_call_index,
    _called_names,
    _const_strings,
    _docstring_consts,
    _dotted,
    _jit_reachable,
    _jit_roots,
    _jit_statics,
    _param_names,
    _resolve_callee,
    _static_name_pool,
    _string_surface,
    iter_functions,
)
from tools.graftlint.rules.collectives import rule_gl04  # noqa: F401
from tools.graftlint.rules.dtype import (  # noqa: F401
    GL02_SCOUT_SURFACE,
    rule_gl02,
)
from tools.graftlint.rules.hotpath import (  # noqa: F401
    rule_gl03,
    rule_gl06,
)
from tools.graftlint.rules.locks import (  # noqa: F401
    GL11_LOCK_MAP,
    rule_gl11,
)
from tools.graftlint.rules.snapshot import rule_gl01  # noqa: F401
from tools.graftlint.rules.statics import rule_gl05  # noqa: F401

ALL_RULES = (rule_gl01, rule_gl02, rule_gl03, rule_gl04, rule_gl05,
             rule_gl06, rule_gl11)

# codes the AST tier checks (the CLI uses this to scope baseline
# staleness: a deep-tier baseline entry is not "stale" on a run that
# never executed the deep rules)
AST_CODES = ("GL01", "GL02", "GL03", "GL04", "GL05", "GL06", "GL11")
