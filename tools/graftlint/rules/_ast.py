"""Shared AST machinery for the graftlint rule set.

Every rule module in this package keys off the same small vocabulary:
function iteration (methods get ``Class.method`` qualnames), dotted-name
rendering, jit-root discovery (decorated entries AND the
``jax.jit(shard_map_compat(body))`` builder pattern), the intra-package
call index, and the jitted-reachability BFS shared by GL03/GL06.

Round 17 fix: the reachability BFS resolves call targets wrapped in
``functools.partial(...)`` — ``cb = functools.partial(helper, k)``
inside a jit-reachable function makes ``helper`` part of the traced
program, but the pre-round-17 walk only followed direct calls, so a
host sync (or telemetry emit) inside a partial-wrapped helper was
silently invisible to GL03/GL06.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint.core import LintModule


def iter_functions(tree: ast.Module
                   ) -> Iterator[Tuple[str, ast.FunctionDef]]:
    """Top-level functions and class methods as (qualname, node).
    Nested closures stay inside their parent's subtree (a function's
    "scope" for every rule below is its whole subtree)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); '' if not dotted."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _jit_statics(fn: ast.FunctionDef) -> Optional[Tuple[str, ...]]:
    """If ``fn`` is decorated as a jitted entry, return its declared
    static_argnames (possibly empty); None when not jitted.

    Recognized forms: ``@jax.jit``, ``@jit``, and
    ``@[functools.]partial(jax.jit, static_argnames=(...))``.
    """
    for dec in fn.decorator_list:
        d = _dotted(dec)
        if d in ("jax.jit", "jit"):
            return ()
        if isinstance(dec, ast.Call):
            head = _dotted(dec.func)
            if head not in ("functools.partial", "partial"):
                continue
            if not dec.args or _dotted(dec.args[0]) not in ("jax.jit",
                                                            "jit"):
                continue
            for kw in dec.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    return tuple(_const_strings(kw.value))
            return ()
    return None


def _const_strings(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_const_strings(e))
        return out
    return []


def _param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs]
            + ([a.vararg.arg] if a.vararg else [])
            + ([a.kwarg.arg] if a.kwarg else []))


def _docstring_consts(node: ast.AST) -> Set[int]:
    """ids of the Constant nodes that are docstrings anywhere under
    ``node`` — prose must not count as code-level accounting: a
    docstring *mentioning* a counter or a field name is not the same
    as persisting/incrementing it."""
    out: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Module)):
            body = n.body
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def _string_surface(node: ast.AST) -> Set[str]:
    """Every way a field name can be 'mentioned' by snapshot code:
    string constants (dict keys, tuple-of-names tables, np.savez keys)
    and keyword-argument names (``dict(tasks=0)``, ``overflow=ovf``).
    Docstrings are excluded — prose is not persistence."""
    docs = _docstring_consts(node)
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and id(n) not in docs:
            out.add(n.value)
        elif isinstance(n, ast.keyword) and n.arg:
            out.add(n.arg)
    return out


def _called_names(node: ast.AST) -> Set[str]:
    """Simple callee names (both ``f(...)`` and ``mod.f(...)``)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Name):
                out.add(n.func.id)
            elif isinstance(n.func, ast.Attribute):
                out.add(n.func.attr)
    return out


def _jit_roots(mod: LintModule
               ) -> List[Tuple[str, ast.FunctionDef, Tuple[str, ...]]]:
    """Jitted entries of a module: decorated defs, plus local function
    names passed (possibly through wrappers like ``shard_map_compat``)
    into a ``jax.jit(...)`` call — the builder pattern the sharded
    engines use."""
    roots = []
    for qn, fn in iter_functions(mod.tree):
        statics = _jit_statics(fn)
        if statics is not None:
            roots.append((qn, fn, statics))
    local_defs: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(mod.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs.setdefault(n.name, n)
    seen = {qn for qn, _, _ in roots}

    def names_in(node):
        for x in ast.walk(node):
            if isinstance(x, ast.Name):
                yield x.id

    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and _dotted(n.func) in ("jax.jit",
                                                           "jit"):
            for arg in n.args[:1]:
                for name in names_in(arg):
                    fn = local_defs.get(name)
                    if fn is not None and name not in seen:
                        seen.add(name)
                        statics = tuple(
                            s for kw in n.keywords
                            if kw.arg in ("static_argnames",
                                          "static_argnums")
                            for s in _const_strings(kw.value))
                        roots.append((name, fn, statics))
    return roots


def _build_call_index(modules: List[LintModule]
                      ) -> Dict[str, Dict[str, ast.FunctionDef]]:
    """modkey -> {top-level function/method name -> node}."""
    return {m.modkey: dict(iter_functions(m.tree)) for m in modules}


def _resolve_name_or_attr(mod: LintModule, node: ast.AST,
                          index: Dict[str, Dict[str, ast.FunctionDef]]
                          ) -> Optional[Tuple[str, str]]:
    """(modkey, qualname) when a bare Name or ``module.attr`` node
    resolves to an intra-package function via this module's import
    bindings; None otherwise."""
    if isinstance(node, ast.Name):
        if node.id in index.get(mod.modkey, {}):
            return mod.modkey, node.id
        imp = mod.name_imports.get(node.id)
        if imp is not None:
            base, orig = imp
            if orig in index.get(base, {}):
                return base, orig
        return None
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name):
        target_mod = mod.module_aliases.get(node.value.id)
        if target_mod is not None and node.attr in index.get(target_mod,
                                                             {}):
            return target_mod, node.attr
    return None


def _resolve_callee(mod: LintModule, call: ast.Call,
                    index: Dict[str, Dict[str, ast.FunctionDef]]
                    ) -> Optional[Tuple[str, str]]:
    """(modkey, qualname) of an intra-package callee, else None.

    ``functools.partial(f, ...)`` resolves to ``f`` (round-17 fix):
    partial-wrapping a function hands the WRAPPED function to whatever
    consumes the callable (a lax.scan body, a kernel builder, a
    deferred call), so for reachability purposes building the partial
    IS calling the target."""
    f = call.func
    resolved = _resolve_name_or_attr(mod, f, index)
    if resolved is not None:
        return resolved
    if _dotted(f) in ("functools.partial", "partial") and call.args:
        return _resolve_name_or_attr(mod, call.args[0], index)
    return None


def _static_name_pool(modules: List[LintModule]) -> Set[str]:
    """Union of every declared static argname in the package: a name
    in this pool passed to ``int()`` inside a traced body is a
    trace-time config coercion, not a host sync."""
    pool: Set[str] = set()
    for mod in modules:
        for _, fn, statics in _jit_roots(mod):
            pool.update(statics)
    return pool


def _arg_is_trace_safe(node: ast.AST, static_pool: Set[str]) -> bool:
    """int()/float() args that are NOT host syncs: constants, shape
    reads (static under tracing), and static-config names."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim",
                                                       "dtype"):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return True
    names = [n.id for n in ast.walk(node) if isinstance(n, ast.Name)]
    if names and all(nm in static_pool for nm in names):
        return True
    return not names    # pure-constant expression


def _jit_reachable(modules: List[LintModule]):
    """BFS the intra-package call graph from every jitted root.

    Returns ``(visited, lookup)``: the reachable ``(modkey, qualname)``
    set and a resolver to each function's AST node. Shared by GL03
    (host syncs) and GL06 (telemetry publishes) — both defend the same
    boundary: code reachable from a jitted root runs under tracing.
    ``functools.partial(f, ...)`` edges are followed like direct calls
    (see :func:`_resolve_callee`).
    """
    index = _build_call_index(modules)
    mod_by_key = {m.modkey: m for m in modules}
    # nested defs too: builder-pattern roots (jax.jit(wrap(body)) where
    # body is a closure) are not top-level functions
    all_defs: Dict[str, Dict[str, ast.FunctionDef]] = {}
    for m in modules:
        d: Dict[str, ast.FunctionDef] = {}
        for n in ast.walk(m.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                d.setdefault(n.name, n)
        all_defs[m.modkey] = d

    def _lookup(modkey: str, qn: str) -> Optional[ast.FunctionDef]:
        return index[modkey].get(qn) or all_defs[modkey].get(qn)
    # BFS the reachable set
    queue: List[Tuple[str, str]] = []
    for mod in modules:
        for qn, fn, _ in _jit_roots(mod):
            queue.append((mod.modkey, qn))
    visited: Set[Tuple[str, str]] = set()
    while queue:
        key = queue.pop()
        if key in visited:
            continue
        visited.add(key)
        modkey, qn = key
        mod = mod_by_key[modkey]
        fn = _lookup(modkey, qn)
        if fn is None:
            continue
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                callee = _resolve_callee(mod, n, index)
                if callee is not None and callee not in visited:
                    queue.append(callee)
    return visited, _lookup
