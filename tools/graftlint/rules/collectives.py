"""GL04 — uncounted collectives in the dd engine (AST tier).

The SEMANTIC twin of this rule is GL07 (``tools/graftlint/deep.py``):
GL04 sees only what the source spells — a collective hidden behind a
``shard_map`` body builder, a ``lax.cond`` branch, or a helper in
another module is invisible here, which is exactly why the deep tier
traces the real jitted dd/stream programs and censuses the collective
primitives that tracing actually captured.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List

from tools.graftlint.core import LintModule, Violation
from tools.graftlint.rules._ast import (_docstring_consts, _dotted,
                                        iter_functions)

_COLLECTIVES = {"psum", "all_gather", "ppermute", "pmax", "pmin",
                "pmean", "psum_scatter", "all_to_all"}
_GL04_SCOPE = re.compile(r"(sharded_walker|mesh)\.py$")


def rule_gl04(modules: List[LintModule]) -> Iterator[Violation]:
    """GL04: every collective in the dd engine must be paired with
    ``crounds`` accounting.

    The dd walker's headline claim (2.4-3.0 collective rounds/cycle vs
    legacy's 7-10.5) is backed by the device-counted ``crounds``
    counter; a collective added without touching ``crounds`` silently
    falsifies that accounting.  Mechanically: any top-level function in
    ``sharded_walker.py``/``mesh.py`` whose subtree performs a
    ``lax.psum/all_gather/ppermute/...`` must also reference
    ``crounds`` somewhere in the same subtree (increment, carry field,
    or an explicit pass-through).  Primitives whose collectives are
    counted by their caller belong in the allowlist with that reason.
    """
    for mod in modules:
        if not _GL04_SCOPE.search(mod.path):
            continue
        for qn, fn in iter_functions(mod.tree):
            hits: List[ast.Call] = []
            counted = False
            docs = _docstring_consts(fn)
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    head = _dotted(n.func)
                    parts = head.split(".")
                    if (parts[-1] in _COLLECTIVES
                            and (len(parts) == 1
                                 or parts[-2] in ("lax", "jax"))):
                        hits.append(n)
                if isinstance(n, ast.Name) and "crounds" in n.id:
                    counted = True
                elif isinstance(n, ast.Attribute) \
                        and "crounds" in n.attr:
                    counted = True
                elif isinstance(n, ast.keyword) and n.arg \
                        and "crounds" in n.arg:
                    counted = True
                elif isinstance(n, ast.Constant) \
                        and isinstance(n.value, str) \
                        and "crounds" in n.value \
                        and id(n) not in docs:
                    # a docstring saying "crounds is handled by the
                    # caller" is prose — the allowlist (with a
                    # reviewable reason) is the only sanctioned
                    # caller-counts-it escape hatch
                    counted = True
            if hits and not counted:
                yield Violation(
                    code="GL04", path=mod.path, line=hits[0].lineno,
                    symbol=qn,
                    message=(
                        f"{qn} performs {len(hits)} collective(s) "
                        f"(lax.psum/all_gather/...) but never touches "
                        f"the crounds counter: the device-counted "
                        f"collective-round claims no longer cover "
                        f"this path. Increment crounds at the "
                        f"boundary, or allowlist with the reason the "
                        f"caller counts it."))
