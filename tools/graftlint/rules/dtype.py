"""GL02 — f64 dtype discipline (plus the declared scout-dtype surface)."""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Tuple

from tools.graftlint.core import LintModule, Violation
from tools.graftlint.rules._ast import _dotted, iter_functions

# Creation calls whose dtype defaults are config-dependent (f32 without
# jax_enable_x64).  jnp.array/asarray are only flagged for literal
# payloads: wrapping an existing traced array inherits its dtype.
_GL02_CREATORS = {"zeros", "ones", "empty", "full", "arange",
                  "linspace"}
_GL02_DTYPE_POSITION = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                        "array": 1, "asarray": 1}
# The ds (double-double) representation IS a pair of f32 limbs: its
# kernels are f32 by construction, not by accident.
_GL02_F32_EXEMPT = re.compile(r"ops/(ds_kernel|pow2|ds)\.py$")

# Round-12 DECLARED SCOUT-DTYPE SURFACE: the mixed-precision scouting
# pass is DELIBERATELY f32 — but only where declared. Each entry names
# a module (path suffix), the symbols (function qualnames, or "*" for
# the whole module) allowed to reference f32, and the REVIEWED reason.
# This is a declaration, not a baseline: f32 outside the listed
# (module, symbol) pairs still fails GL02, and additions here are a
# code-reviewed API change, never a silent baseline growth
# (tests/test_graftlint.py pins both directions).
GL02_SCOUT_SURFACE = {
    "ops/scout_kernel.py": {
        "*": "the declared f32 scout surface itself: a single-precision "
             "ds-API twin evaluated ONLY by the walker's scout pass — "
             "f32 is the module's entire purpose, and every scout "
             "decision it feeds is either decisively-split (guard band) "
             "or re-taken in full ds by the confirm pass.",
    },
}


def _scout_surface_entry(path: str, qn: str):
    """The declared scout-surface reason covering (module, symbol), or
    None when the pair is not declared."""
    for suffix, symbols in GL02_SCOUT_SURFACE.items():
        if path.endswith(suffix):
            if "*" in symbols:
                return symbols["*"]
            if qn in symbols:
                return symbols[qn]
            # bare function name of a ClassName.method qualname
            if qn.split(".")[-1] in symbols:
                return symbols[qn.split(".")[-1]]
    return None


def _is_literal_payload(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex, bool))
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literal_payload(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literal_payload(node.operand)
    return False


def rule_gl02(modules: List[LintModule]) -> Iterator[Violation]:
    """GL02: f64 dtype discipline in ``parallel/`` and ``ops/``.

    Flags (a) dtype-less ``jnp.zeros/ones/empty/full/arange/linspace``
    and literal-payload ``jnp.array/asarray`` — their dtype is whatever
    ``jax_enable_x64`` happens to be, i.e. f32 in any embedding that
    forgot the flag, silently downcasting an accumulator path; and
    (b) ``float32`` references outside the ds-limb modules (ds kernels
    are f32 *by representation*; everywhere else f32 in a numeric path
    is a downcast hazard).  Literal arithmetic (``0.5 * x``) is NOT
    flagged: under weak typing literals adopt the array operand's
    dtype, so the hazard is creation, not arithmetic.

    Round 12: the DECLARED scout-dtype surface (``GL02_SCOUT_SURFACE``
    — module + symbol list, per-entry reviewed reason) carves out the
    mixed-precision scouting pass from the float32 check only; the
    dtype-less-creation check still applies inside it, and f32 outside
    the declared pairs still fails."""
    for mod in modules:
        if "/parallel/" not in "/" + mod.path \
                and "/ops/" not in "/" + mod.path:
            continue
        f32_hits: Dict[str, Tuple[int, int]] = {}
        for qn, fn in iter_functions(mod.tree):
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    head = _dotted(n.func)
                    parts = head.split(".")
                    if len(parts) == 2 and parts[0] in ("jnp", "jax_np"):
                        name = parts[1]
                        has_dtype = any(kw.arg == "dtype"
                                        for kw in n.keywords)
                        pos = _GL02_DTYPE_POSITION.get(name)
                        if pos is not None and len(n.args) > pos:
                            has_dtype = True
                        if name in _GL02_CREATORS and not has_dtype \
                                and name not in ("array", "asarray"):
                            yield Violation(
                                code="GL02", path=mod.path,
                                line=n.lineno,
                                symbol=f"{qn}:dtype-less-{name}",
                                message=(
                                    f"jnp.{name}(...) without an "
                                    f"explicit dtype in a numeric "
                                    f"path: the result is f32 unless "
                                    f"jax_enable_x64 is set — pass "
                                    f"dtype=jnp.float64 (or the "
                                    f"intended integer dtype)."))
                        elif name in ("array", "asarray") \
                                and not has_dtype and n.args \
                                and _is_literal_payload(n.args[0]):
                            yield Violation(
                                code="GL02", path=mod.path,
                                line=n.lineno,
                                symbol=f"{qn}:dtype-less-{name}",
                                message=(
                                    f"jnp.{name}(<literal>) without "
                                    f"dtype: literal payloads default "
                                    f"to the x64-flag dtype — make "
                                    f"the f64 (or integer) intent "
                                    f"explicit."))
                if not _GL02_F32_EXEMPT.search(mod.path) \
                        and _scout_surface_entry(mod.path, qn) is None:
                    is_f32 = (
                        (isinstance(n, ast.Attribute)
                         and n.attr == "float32")
                        or (isinstance(n, ast.Constant)
                            and n.value == "float32"))
                    if is_f32 and qn not in f32_hits:
                        f32_hits[qn] = (n.lineno, 1)
                    elif is_f32:
                        line, cnt = f32_hits[qn]
                        f32_hits[qn] = (line, cnt + 1)
        for qn, (line, cnt) in f32_hits.items():
            yield Violation(
                code="GL02", path=mod.path, line=line,
                symbol=f"{qn}:float32",
                message=(
                    f"{cnt} float32 reference(s) in {qn}: f32 in a "
                    f"numeric path silently downcasts the f64 "
                    f"accumulator chain. If the f32 is deliberate "
                    f"(ds limbs, lane-state packing), allowlist this "
                    f"function with that reason."))
