"""GL03 + GL06 — the traced-hot-path boundary rules.

Both rules defend the same line: code reachable from a jitted root runs
under tracing, so host syncs (GL03) and telemetry publishes (GL06) in
there either break tracing, fire at trace time, or force a device
round-trip per cycle. They share the :func:`_jit_reachable` BFS.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from tools.graftlint.core import LintModule, Violation
from tools.graftlint.rules._ast import (_arg_is_trace_safe, _dotted,
                                        _jit_reachable,
                                        _static_name_pool)

_HOST_SYNC_ATTRS = {"device_get", "device_put", "block_until_ready",
                    "item", "tolist"}
_NP_ALIASES = {"np", "numpy", "onp"}


def rule_gl03(modules: List[LintModule]) -> Iterator[Violation]:
    """GL03: host synchronization inside the traced hot path.

    Walks the intra-package call graph from every jitted root (the
    ``@jax.jit`` entries of walker.py/stream.py and the
    ``jax.jit(shard_map_compat(...))`` builders of the sharded
    engines) and flags, in any reachable function body:
    ``jax.device_get/device_put``, ``.block_until_ready()``,
    ``.item()/.tolist()``, ``np.*`` calls on non-constant arguments,
    and ``int()/float()/bool()`` coercions of traced values.  Under
    ``jit`` these either fail at trace time in the best case or —
    with AOT-style retracing — force a device round-trip per cycle in
    the hot loop, which is exactly the failure mode the device-counted
    ``crounds``/phase claims exist to rule out."""
    mod_by_key = {m.modkey: m for m in modules}
    static_pool = _static_name_pool(modules)
    visited, _lookup = _jit_reachable(modules)
    for modkey, qn in sorted(visited):
        mod = mod_by_key[modkey]
        fn = _lookup(modkey, qn)
        if fn is None:
            continue
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            head = _dotted(n.func)
            parts = head.split(".")
            sync = None
            if head in ("jax.device_get", "jax.device_put"):
                sync = head
            elif isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("block_until_ready", "item",
                                        "tolist"):
                sync = f".{n.func.attr}()"
            elif len(parts) == 2 and parts[0] in _NP_ALIASES:
                # np.float32(eps) on a static config name is trace-time
                # constant construction, not a sync
                if any(not _arg_is_trace_safe(a, static_pool)
                       for a in n.args):
                    sync = head
            elif isinstance(n.func, ast.Name) \
                    and n.func.id in ("int", "float", "bool") \
                    and n.args \
                    and not _arg_is_trace_safe(n.args[0], static_pool):
                sync = f"{n.func.id}()"
            if sync is None:
                continue
            yield Violation(
                code="GL03", path=mod.path, line=n.lineno,
                symbol=f"{qn}:{sync}",
                message=(
                    f"{sync} inside {qn}, which is reachable from a "
                    f"jitted root: a host sync in the traced hot path "
                    f"either breaks tracing or forces a device "
                    f"round-trip per cycle. Hoist it to the host "
                    f"driver, or allowlist with the reason it only "
                    f"runs at trace time."))


# The obs-layer publish/emit surface (obs.telemetry / obs.registry /
# obs.spans method names). `.set` is deliberately ABSENT: jax's
# `x.at[i].set(v)` shares the attribute name, and gauges are only
# reachable through the obs-imported handles the name check below
# already covers. Round 19 extends the surface to the new emit
# sites: the request-trace helpers (request_span / request_event /
# span_detached), the SLO burn evaluator (evaluate_slo), and the
# federation merge (ingest_dump) — all boundary-hook-only like the
# rest.
_GL06_API = {"inc", "set_max", "observe", "event", "span",
             "publish_run", "publish_phase", "publish_compile_cache",
             "publish_compile", "publish_chip_balance", "record_phase",
             "stream_counter", "stream_gauge", "emit_event",
             "request_span", "request_event", "span_detached",
             "evaluate_slo", "ingest_dump"}


def _imports_obs(mod: LintModule) -> bool:
    """Whether the module binds anything from the obs subpackage."""
    if any(v == "obs" or v.startswith("obs/")
           for v in mod.module_aliases.values()):
        return True
    return any(base == "obs" or base.startswith("obs/")
               for base, _ in mod.name_imports.values())


def rule_gl06(modules: List[LintModule]) -> Iterator[Violation]:
    """GL06: telemetry reads/writes (registry publishes, span/event
    emits) may only occur in boundary-hook functions — never inside a
    function reachable from a jitted root.

    The telemetry layer's contract is "one device fetch per boundary,
    publishes are host dict arithmetic on values the boundary already
    pulled" (obs/__init__.py). A publish that drifts into the traced
    cycle body breaks it two ways at once: the Python side effect
    runs at TRACE time (the registry records one phantom sample per
    compile, not per execution — silently wrong counts), and any
    value it needs forces the GL03 host-sync shape. Mechanically: in
    any function reachable from a jitted root (the GL03 BFS), flag
    (a) calls to names imported from ``obs`` modules, and (b) — in
    modules that import obs — attribute calls spelling an obs API
    method (``.inc``/``.observe``/``.event``/``.span``/
    ``publish_*``/...)."""
    mod_by_key = {m.modkey: m for m in modules}
    visited, _lookup = _jit_reachable(modules)
    for modkey, qn in sorted(visited):
        mod = mod_by_key[modkey]
        fn = _lookup(modkey, qn)
        if fn is None:
            continue
        obs_mod = _imports_obs(mod)
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            hit = None
            f = n.func
            if isinstance(f, ast.Name):
                imp = mod.name_imports.get(f.id)
                if imp is not None and (imp[0] == "obs"
                                        or imp[0].startswith("obs/")):
                    hit = f.id
            elif isinstance(f, ast.Attribute):
                if obs_mod and f.attr in _GL06_API:
                    hit = f.attr
                # obs_module.anything(...) through a module alias
                elif isinstance(f.value, ast.Name):
                    tgt = mod.module_aliases.get(f.value.id)
                    if tgt is not None and (tgt == "obs"
                                            or tgt.startswith("obs/")):
                        hit = f"{f.value.id}.{f.attr}"
            if hit is None:
                continue
            yield Violation(
                code="GL06", path=mod.path, line=n.lineno,
                symbol=f"{qn}:{hit}",
                message=(
                    f"telemetry publish/emit {hit!r} inside {qn}, "
                    f"which is reachable from a jitted root: the "
                    f"side effect fires at trace time (one phantom "
                    f"sample per compile) and its inputs force a "
                    f"host sync. Move the publish to the host "
                    f"boundary hook that already holds the fetched "
                    f"values."))
