"""GL11 — lock-discipline lint for the serving runtime's shared state.

Round 16 fixed two real ingest races BY HAND REVIEW (CHANGES PR 10):
an ingest acknowledgment could land in a dead engine during the
supervisor's backoff window (the handle was cleared outside the engine
lock), and concurrent stdout JSONL lines could interleave mid-record.
This rule is the regression armor: a DECLARED lock map
(``GL11_LOCK_MAP``) names, per module, the attributes that are shared
across threads and the lock that owns them; any read or write of a
guarded attribute outside a ``with <lock>:`` block flags.

The discipline is lexical (AST tier), which is exactly what makes it
enforceable: the repo's convention is that every cross-thread touch
sits visibly inside a ``with self._lock`` block of the owning class
(``runtime/ingest.py``'s ``EngineHandle``), and the engines themselves
stay single-threaded. ``__init__`` (and any other declared
``unlocked_ok`` function) is exempt — an object under construction is
not yet shared.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from tools.graftlint.core import LintModule, Violation
from tools.graftlint.rules._ast import iter_functions

# The declared lock map. Like GL02_SCOUT_SURFACE this is a REVIEWED
# declaration, not a baseline: every entry carries the reason its
# guarded set is what it is, additions are a code-reviewed API change,
# and tests pin that reasons exist. ``guarded`` may be empty — that is
# itself a contract statement ("this module holds no cross-thread
# mutable state"), kept here so the next thread added to the module
# has to meet this rule head-on instead of discovering it post-race.
GL11_LOCK_MAP = {
    "runtime/ingest.py": {
        "locks": ("_lock",),
        "guarded": ("_eng",),
        "unlocked_ok": ("__init__",),
        "reason": (
            "EngineHandle._eng is the live-engine publication cell "
            "shared between the serve phase loop and the ingest "
            "handler threads: the PR-10 ack-after-engine-death race "
            "was exactly a touch of this slot outside the engine "
            "lock (an ack landing in a dead engine vanishes at "
            "resume). Every read/write goes through a with "
            "self._lock block; __init__ is exempt because the "
            "handle is not yet shared during construction."),
    },
    "runtime/stream.py": {
        "locks": ("_lock",),
        "guarded": (),
        "unlocked_ok": (),
        "reason": (
            "StreamEngine is single-threaded BY DESIGN: every "
            "cross-thread access (ingest submit, shed ledger reads, "
            "graceful-shutdown snapshot) is serialized by the serve "
            "loop's EngineHandle lock (runtime/ingest.py), so the "
            "engine itself owns no lock and no guarded attrs. The "
            "empty guarded set records that contract — a thread "
            "spawned INSIDE stream.py must declare its shared attrs "
            "here (and take a lock) or fail review."),
    },
    "runtime/checkpoint.py": {
        "locks": ("_cv", "_WRITER_LOCK"),
        "guarded": ("_q", "_busy", "_err", "_closed"),
        "unlocked_ok": ("__init__", "_raise_pending"),
        "reason": (
            "CheckpointWriter's queue, worker-busy flag, parked "
            "error, and shutdown latch are shared between the serve "
            "loop (submit/flush/close) and the background writer "
            "thread (_run); every touch sits inside a with self._cv "
            "block — the condition doubles as the mutex — and the "
            "module-level singleton is published under _WRITER_LOCK. "
            "_raise_pending is exempt because both its callers "
            "(submit, flush) invoke it while already holding _cv; "
            "GL11's lexical scan cannot see a caller-held lock, and "
            "splitting the take-and-swap into the callers would "
            "duplicate the error-rethrow dance at both sites."),
    },
}


def _with_mentions_lock(item: ast.withitem, locks) -> bool:
    """True when a with-item's context expression spells one of the
    declared lock names (``self._lock``, ``handle._lock``, a bare
    ``_lock`` local, or a ``handle.lock()`` accessor returning it)."""
    for n in ast.walk(item.context_expr):
        if isinstance(n, ast.Attribute) and n.attr in locks:
            return True
        if isinstance(n, ast.Name) and n.id in locks:
            return True
    return False


def rule_gl11(modules: List[LintModule]) -> Iterator[Violation]:
    """GL11: reads/writes of declared cross-thread attributes outside
    the owning ``with <lock>`` block.

    For every module with a ``GL11_LOCK_MAP`` entry: each access to a
    guarded attribute (``anything._eng`` — attribute spelling is the
    identity, mirroring how the PR-10 race was a ``holder`` slot
    reachable from two threads under any alias) must sit lexically
    inside a ``with`` whose context expression mentions one of the
    declared lock names. Functions in the entry's ``unlocked_ok``
    tuple (``__init__`` by convention) are exempt."""
    for mod in modules:
        entry = None
        for suffix, e in GL11_LOCK_MAP.items():
            if mod.path.endswith(suffix):
                entry = e
                break
        if entry is None or not entry["guarded"]:
            continue
        locks = tuple(entry["locks"])
        guarded = set(entry["guarded"])
        exempt = set(entry.get("unlocked_ok", ()))

        for qn, fn in iter_functions(mod.tree):
            if qn in exempt or qn.split(".")[-1] in exempt:
                continue
            seen: Set[Tuple[str, str]] = set()

            def scan(node: ast.AST, held: bool):
                for child in ast.iter_child_nodes(node):
                    child_held = held
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        if any(_with_mentions_lock(it, locks)
                               for it in child.items):
                            child_held = True
                    if isinstance(child, ast.Attribute) \
                            and child.attr in guarded and not held:
                        key = (qn, child.attr)
                        if key not in seen:
                            seen.add(key)
                            yield Violation(
                                code="GL11", path=mod.path,
                                line=child.lineno,
                                symbol=f"{qn}:{child.attr}",
                                message=(
                                    f"{qn} touches the cross-thread "
                                    f"attribute {child.attr!r} outside "
                                    f"the owning with-{'/'.join(locks)}"
                                    f" block: this is the PR-10 "
                                    f"ack-after-engine-death race "
                                    f"shape — another thread can "
                                    f"observe or clear the handle "
                                    f"mid-sequence. Wrap the access "
                                    f"in the declared lock, or add "
                                    f"the function to unlocked_ok "
                                    f"with a reviewed reason."))
                    yield from scan(child, child_held)

            yield from scan(fn, False)
