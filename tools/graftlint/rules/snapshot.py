"""GL01 — snapshot-identity completeness."""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from tools.graftlint.core import LintModule, Violation
from tools.graftlint.rules._ast import (_called_names, _string_surface,
                                        iter_functions)

_CHECKPOINT_APIS = {
    "save_family_checkpoint", "load_family_checkpoint",
    "save_checkpoint", "load_checkpoint",
    "_family_identity", "_family_ckpt_identity", "_stream_identity",
    "_dd_ckpt_identity",
}
_SNAPSHOT_NAME_RE = re.compile(r"identity|checkpoint|snapshot|resume",
                               re.IGNORECASE)

# Spelling bridges between carry fields and their on-disk names.  Kept
# deliberately tiny: a rename that breaks one of these should be FELT.
_GL01_ALIASES: Dict[str, Set[str]] = {
    "bag": {"bag_cols"},
    "bag_l": {"l"}, "bag_r": {"r"}, "bag_th": {"th"},
    "bag_meta": {"meta"},
    "maxd": {"max_depth"},
}


def _carry_classes(mod: LintModule
                   ) -> List[Tuple[ast.ClassDef, List[Tuple[str, int]]]]:
    """NamedTuple/dataclass definitions named ``*Carry`` with their
    (field, line) lists."""
    from tools.graftlint.rules._ast import _dotted
    out = []
    for node in mod.tree.body:
        if not (isinstance(node, ast.ClassDef)
                and node.name.endswith("Carry")):
            continue
        is_nt = any(_dotted(b).split(".")[-1] == "NamedTuple"
                    for b in node.bases)
        is_dc = any(_dotted(d).split(".")[-1] == "dataclass"
                    or (isinstance(d, ast.Call)
                        and _dotted(d.func).split(".")[-1] == "dataclass")
                    for d in node.decorator_list)
        if not (is_nt or is_dc):
            continue
        fields = [(s.target.id, s.lineno) for s in node.body
                  if isinstance(s, ast.AnnAssign)
                  and isinstance(s.target, ast.Name)]
        out.append((node, fields))
    return out


def rule_gl01(modules: List[LintModule]) -> Iterator[Violation]:
    """GL01: every field of every walker/stream/dd carry container must
    be represented on the checkpoint identity surface.

    The PR-2 near-miss this encodes: ``refill_slots`` changed the
    meaning of the persisted state but was not part of the snapshot
    identity, so a refill snapshot could silently resume a legacy run.
    Mechanically: for each ``*Carry`` NamedTuple/dataclass that is
    referenced by the module's snapshot code (directly, or by a
    function the snapshot code calls — the run entry whose result gets
    persisted), every field name must appear among the string
    constants / keyword names of the snapshot functions themselves (or
    of ``runtime/checkpoint.py``), modulo the tiny documented alias
    map.  A field the snapshot surface never mentions is state the
    resume path cannot restore."""
    global_surface: Set[str] = set()
    for mod in modules:
        if mod.path.endswith("runtime/checkpoint.py"):
            global_surface |= _string_surface(mod.tree)
    for mod in modules:
        carries = _carry_classes(mod)
        if not carries:
            continue
        funcs = dict(iter_functions(mod.tree))
        contributing = {
            qn: fn for qn, fn in funcs.items()
            if _SNAPSHOT_NAME_RE.search(qn)
            or (_called_names(fn) & _CHECKPOINT_APIS)
        }
        if not contributing:
            continue
        surface = set(global_surface)
        referencing: List[ast.AST] = []
        one_hop: Set[str] = set()
        for fn in contributing.values():
            surface |= _string_surface(fn)
            referencing.append(fn)
            one_hop |= _called_names(fn)
        for qn, fn in funcs.items():
            if qn in one_hop and qn not in contributing:
                referencing.append(fn)
        in_scope_names: Set[str] = set()
        for node in referencing:
            for n in ast.walk(node):
                if isinstance(n, ast.Name):
                    in_scope_names.add(n.id)
        for cls, fields in carries:
            if cls.name not in in_scope_names:
                continue        # kernel-internal carry, never persisted
            for field, line in fields:
                names = {field} | _GL01_ALIASES.get(field, set())
                if names & surface:
                    continue
                yield Violation(
                    code="GL01", path=mod.path, line=line,
                    symbol=f"{cls.name}.{field}",
                    message=(
                        f"carry field {cls.name}.{field} is absent from "
                        f"the snapshot identity surface: no snapshot/"
                        f"identity function in this module mentions "
                        f"{sorted(names)} — a resumed run cannot "
                        f"restore it. Persist it (bag_cols/totals/"
                        f"identity), or allowlist with the reason it "
                        f"is derived state."))
