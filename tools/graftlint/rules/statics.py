"""GL05 — static-arg drift on jitted entries."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint.core import LintModule, Violation
from tools.graftlint.rules._ast import (_dotted, _jit_roots,
                                        _param_names, iter_functions)

_HASHABLE_ANNOTATIONS = {"int", "float", "bool", "str", "Callable",
                         "Rule"}


def _is_config_param(arg: ast.arg, default: Optional[ast.AST]) -> bool:
    ann = arg.annotation
    if ann is not None:
        if _dotted(ann).split(".")[-1] in _HASHABLE_ANNOTATIONS:
            return True
        # Callable[..., X] — subscripted form
        if isinstance(ann, ast.Subscript) \
                and _dotted(ann.value).split(".")[-1] == "Callable":
            return True
    if default is not None and isinstance(default, ast.Constant) \
            and isinstance(default.value, (int, float, bool, str)) \
            and default.value is not None:
        return True
    return False


def rule_gl05(modules: List[LintModule]) -> Iterator[Violation]:
    """GL05: static-arg drift on jitted entries.

    Three drifts, all of which have bitten jitted-config code before:
    (a) a name in ``static_argnames`` that is no longer a parameter —
    silently ignored by jax, so the "static" silently became traced
    after a rename; (b) a hashable config parameter (Callable / int /
    float / bool / str / Rule annotation, or scalar default) that is
    NOT declared static — Callables fail at trace time, scalars trace
    into the program and change numerics-by-config into
    numerics-by-input; (c) a call site feeding a declared static from
    an enclosing loop variable — one recompile per iteration, the
    recompile-storm shape."""
    # (modkey, bare name) -> statics, so same-named jitted functions in
    # different modules don't shadow each other, and call sites resolve
    # through the import bindings instead of by bare-name guesswork
    jit_sigs: Dict[Tuple[str, str], Tuple[str, ...]] = {}
    for mod in modules:
        for qn, fn, statics in _jit_roots(mod):
            jit_sigs[(mod.modkey, qn.split(".")[-1])] = statics
            params = set(_param_names(fn))
            for s in statics:
                if s not in params:
                    yield Violation(
                        code="GL05", path=mod.path, line=fn.lineno,
                        symbol=f"{qn}:{s}:not-a-param",
                        message=(
                            f"static_argnames entry {s!r} of {qn} is "
                            f"not a parameter: jax ignores unknown "
                            f"names, so after a rename the value is "
                            f"silently traced. Fix the declaration."))
            # hashable config params anywhere in the signature:
            # keyword-only (the dominant convention here) AND annotated
            # / scalar-defaulted positional-or-keyword params — a
            # jitted `def f(x, eps: float = 1e-7)` leaks config into
            # the traced signature just the same
            pos = fn.args.posonlyargs + fn.args.args
            pos_defaults = [None] * (len(pos) - len(fn.args.defaults)) \
                + list(fn.args.defaults)
            candidates = list(zip(pos, pos_defaults)) \
                + list(zip(fn.args.kwonlyargs, fn.args.kw_defaults))
            for arg, default in candidates:
                if arg.arg in statics:
                    continue
                if _is_config_param(arg, default):
                    yield Violation(
                        code="GL05", path=mod.path, line=arg.lineno,
                        symbol=f"{qn}:{arg.arg}:undeclared-static",
                        message=(
                            f"keyword-only config param {arg.arg!r} "
                            f"of jitted {qn} is hashable "
                            f"(annotation/default) but not in "
                            f"static_argnames: a Callable here fails "
                            f"at trace time, a scalar gets traced "
                            f"and varies the compiled program's "
                            f"numerics per call. Declare it static "
                            f"or drop the config flavor."))

    def _callee_statics(mod: LintModule, call: ast.Call
                        ) -> Tuple[Optional[str],
                                   Optional[Tuple[str, ...]]]:
        """(display name, statics) when the call site resolves to a
        known jitted function via this module's bindings; (None, None)
        otherwise — an unresolvable ``obj.method(...)`` must not match
        a jitted function that happens to share the bare name."""
        f = call.func
        if isinstance(f, ast.Name):
            if (mod.modkey, f.id) in jit_sigs:
                return f.id, jit_sigs[(mod.modkey, f.id)]
            imp = mod.name_imports.get(f.id)
            if imp is not None and imp in jit_sigs:
                return f.id, jit_sigs[imp]
        elif isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name):
            target_mod = mod.module_aliases.get(f.value.id)
            if target_mod is not None \
                    and (target_mod, f.attr) in jit_sigs:
                return f.attr, jit_sigs[(target_mod, f.attr)]
        return None, None

    # (c) loop-varying statics at call sites, package-wide
    for mod in modules:

        def scan(node: ast.AST, loop_targets: Set[str], qn: str):
            for child in ast.iter_child_nodes(node):
                targets = loop_targets
                if isinstance(child, ast.For):
                    targets = loop_targets | {
                        n.id for n in ast.walk(child.target)
                        if isinstance(n, ast.Name)}
                elif isinstance(child, (ast.ListComp, ast.SetComp,
                                        ast.GeneratorExp, ast.DictComp)):
                    # a call per comprehension element is the same
                    # recompile storm as a for-statement body
                    targets = loop_targets | {
                        n.id for g in child.generators
                        for n in ast.walk(g.target)
                        if isinstance(n, ast.Name)}
                if isinstance(child, ast.Call):
                    name, statics = _callee_statics(mod, child)
                    if statics:
                        for kw in child.keywords:
                            if kw.arg not in statics:
                                continue
                            used = {n.id for n in ast.walk(kw.value)
                                    if isinstance(n, ast.Name)}
                            bad = used & loop_targets
                            if bad:
                                yield Violation(
                                    code="GL05", path=mod.path,
                                    line=child.lineno,
                                    symbol=(f"{qn}:{name}."
                                            f"{kw.arg}:loop-varying"),
                                    message=(
                                        f"call to jitted {name} "
                                        f"feeds static arg "
                                        f"{kw.arg!r} from loop "
                                        f"variable(s) "
                                        f"{sorted(bad)}: one "
                                        f"recompile per iteration "
                                        f"(recompile storm). Hoist "
                                        f"the value or make the "
                                        f"arg traced."))
                yield from scan(child, targets, qn)

        for qn, fn in iter_functions(mod.tree):
            yield from scan(fn, set(), qn)
