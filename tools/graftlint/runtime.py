"""graftlint RUNTIME tier (``--runtime``): GL12-GL14, inter-procedural
AST analysis of the host-side serving stack.

The AST tier (GL01-GL06, GL11) pins the compiled-engine invariants and
the deep tier (GL07-GL10) pins the jaxpr-level ones; this third tier
pins the HOST runtime's two standing contracts, which rounds 16-22
each violated at least once before a reviewer caught it by hand:

* every decision replays bit-identically across kill-and-resume, so
  every piece of mutable host state must ride the snapshot (the
  round-18 spillover counters restarting at zero, the round-22 lease
  ledger only persisting because a reviewer noticed) — **GL12**;
* the serve loop, the ingest/metrics handler threads, and the
  background checkpoint writer share state only through declared
  locks, and nothing blocks while holding one (the round-19
  EngineHandle deadlock: a wedged attempt thread held the handle lock
  inside ``eng.step()`` and every supervised retry then blocked on
  ``with handle.lock():``, burning the whole retry budget) — **GL13**
  and **GL14**.

Like the deep tier this module is pure analysis — no jax import, no
tracing — so ``--runtime`` costs milliseconds and runs on any host.
All three rules emit the standard line-free ``CODE:path:symbol`` keys
and honor pragmas/baseline/``--prune-stale``/``--format json``
through the shared :mod:`tools.graftlint.core` plumbing
(:func:`run_runtime` is literally ``run_lint`` with this tier's rule
tuple).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint.core import LintModule, Violation, run_lint
from tools.graftlint.rules._ast import (_build_call_index, _called_names,
                                        _dotted, _resolve_callee,
                                        _string_surface, iter_functions)
from tools.graftlint.rules.locks import GL11_LOCK_MAP

# ---------------------------------------------------------------------------
# GL12 — snapshot-surface completeness for host state
# ---------------------------------------------------------------------------

# The DECLARED state-class map. Like GL11_LOCK_MAP this is a reviewed
# declaration, not a baseline: listing a class here asserts "instances
# of this class carry state that must survive kill-and-resume", and
# every ``ephemeral`` entry names an attribute that is DELIBERATELY
# not persisted, with the reason a reviewer can check (tests pin that
# reasons exist and are substantive). ``aliases`` bridge attribute
# spellings to their on-disk snapshot keys (``_slot_req`` is
# serialized as ``resident``); kept deliberately tiny so a rename that
# breaks one is FELT.
#
# The ISSUE-named surfaces and where they live: the per-tenant token
# buckets are StreamEngine._tokens/_token_waits (persisted as
# "tokens"/"token_waits"), and the round-22 lease ledger is
# EngineDispatcher._lease_given/_lease_recv (persisted as
# "given"/"recv" inside the "lease" block) plus the coordinator's
# ClusterStreamEngine.ledger — all covered by the entries below
# rather than by separate classes.
GL12_STATE_CLASSES: Dict[str, Dict[str, Dict]] = {
    "runtime/stream.py": {
        "StreamEngine": {
            "why": ("the single-engine serving core: every mutable "
                    "attr is replay state unless declared ephemeral"),
            "aliases": {
                "_slot_req": ("resident",),
                "_records": ("resident",),
            },
            "ephemeral": {
                "_phase_rows_window": (
                    "bounded rolling window feeding the ONLINE "
                    "adapter's observe(); the adapter's own values/"
                    "streaks ride the snapshot (adapt block) and the "
                    "window refills within one cadence interval after "
                    "resume — persisting it would only replay stale "
                    "observations into a resized run"),
                "_admit_window": (
                    "derived at engine build from the admit_window "
                    "kwarg (identity-checked at resume) and the "
                    "store slack; _build_dd_store shrinks it to a "
                    "device multiple deterministically, so the same "
                    "inputs re-derive the same value on resume"),
                "_dd_aw": (
                    "derived per-device admit width computed by "
                    "_build_dd_store from identity-checked config; "
                    "rebuilt on both boot and resume"),
                "_dd_run": (
                    "the compiled dd-walker executable built by "
                    "_build_dd_store; compiled artifacts are rebuilt "
                    "on resume (the persistent compile cache makes "
                    "that cheap), never serialized"),
                "_dd_store": (
                    "the dd-walker's device store layout built by "
                    "_build_dd_store; the device STATE it holds is "
                    "what rides the snapshot (bag cols), the layout "
                    "is re-derived from identity-checked config"),
                "_dd_n_dev": (
                    "device count captured by _build_dd_store; a "
                    "resume may legitimately run on a different "
                    "device count (resize-resume), so persisting it "
                    "would be wrong, not just redundant"),
                "_dd_admit": (
                    "per-phase admission staging handed from _admit "
                    "to the same phase's _step and reset to None; "
                    "never alive at a phase boundary, and snapshots "
                    "are cut only at phase boundaries"),
                "_flight": (
                    "ChipFlightRecorder handle writing to the "
                    "append-only events file; a resumed process "
                    "opens a fresh recorder against the same file"),
                "_chip_phase_rec": (
                    "per-phase chip-attribution staging consumed by "
                    "the same phase's boundary publish and reset to "
                    "None; never alive at a snapshot cut"),
                "_last_fam_live": (
                    "host-side copy of device fam_live fetched at "
                    "each phase boundary; the authoritative state is "
                    "the device bag, which rides the snapshot, and "
                    "the first post-resume phase boundary re-fetches "
                    "it before any result() consumer reads it"),
                "_last_fam_last": (
                    "host-side copy of device fam_last, same "
                    "boundary-refetch contract as _last_fam_live"),
            },
        },
    },
    "runtime/dispatch.py": {
        "EngineDispatcher": {
            "why": ("the multi-engine pool: routing, park/lease "
                    "bookkeeping, and cut manifests are all replay "
                    "state unless declared ephemeral"),
            "aliases": {
                "_lease_given": ("given",),
                "_lease_recv": ("recv",),
                "_parked": ("engines",),
                "_wrappers": ("engines",),
            },
            "ephemeral": {
                "_grid_spans": (
                    "open telemetry span handles for the in-flight "
                    "phase; spans are re-opened by the next phase "
                    "after resume and the events file is append-only, "
                    "so persisting live handles would be meaningless"),
                "_pool_dir": (
                    "derived from the checkpoint path argument at "
                    "construction on BOTH first boot and resume; "
                    "persisting it would pin a snapshot to an "
                    "absolute path and break relocated restores"),
                "_cache_entries_seen": (
                    "compile-cache telemetry watermark (counts NEW "
                    "persistent-cache entries this process observed); "
                    "a resumed process legitimately restarts the "
                    "watermark at the cache's current size — it "
                    "meters compilation work done, not replay state"),
            },
        },
    },
    "runtime/cluster.py": {
        "ClusterStreamEngine": {
            "why": ("the multi-process coordinator: the request "
                    "ledger, spillover queue, and rr cursor are the "
                    "determinism contract across kill-and-resume"),
            "aliases": {
                # the worker manifest rides the checkpoint identity
                # as the "cluster" block (_identity builds it from
                # manifest.identity(); resume verifies against it)
                "manifest": ("cluster",),
            },
            "ephemeral": {
                "_workers": (
                    "live WorkerHandle subprocesses; resume respawns "
                    "workers from the manifest (identity) and "
                    "re-deals in-flight requests from the ledger, so "
                    "process handles are rebuilt, never restored"),
                "_flight": (
                    "per-worker in-flight request map, derived state: "
                    "resume re-deals every non-retired ledger entry "
                    "(phases_after_recovery covers the replayed "
                    "turns), so the flight map is reconstructed from "
                    "the persisted ledger"),
                "_closed": (
                    "process-lifecycle latch (close() idempotency); "
                    "a resumed coordinator is by definition open"),
                "_phases_after_recovery": (
                    "bench/telemetry counter of post-recovery turns "
                    "in THIS process lifetime, reported on the "
                    "summary line; counting across resumes would "
                    "double-report recovery work already summarized "
                    "by the previous segment"),
                "_rid_spans": (
                    "open request-span telemetry handles; the resume "
                    "path re-opens spans for restored live rids "
                    "(restored=True attr) into the append-only "
                    "events file, so live handles are rebuilt"),
                "redeal_walls": (
                    "bench telemetry (wall seconds spent re-dealing "
                    "after worker loss) reported on the summary line "
                    "of the process that did the re-dealing; not "
                    "replay state — a resumed run re-deals afresh "
                    "and its own wall cost starts at zero"),
            },
        },
    },
    "backends/spillover.py": {
        "SpilloverExecutor": {
            "why": ("the CPU spillover lane's counters feed the "
                    "round-18 gap this rule generalizes: totals "
                    "restarting at zero under-reported spilled work "
                    "after resume"),
            "aliases": {
                # persisted BY THE OWNING ENGINE's snapshot (stream/
                # cluster totals blocks), spelled with the spill_
                # prefix there:
                "requests_total": ("spill_requests_total",),
                "tasks_total": ("spill_tasks_total",),
            },
            "ephemeral": {
                "wall_total": (
                    "wall-clock seconds of spillover compute in THIS "
                    "process, reported on the summary line; wall "
                    "time is not replayable state (a resumed run's "
                    "own wall cost starts at zero by definition)"),
            },
        },
    },
    "runtime/guard.py": {
        "GracefulShutdown": {
            "why": ("shutdown intent must not be lost across the "
                    "drain: the engine snapshot (pending queue) is "
                    "the persisted half, these attrs are the "
                    "process-local half"),
            "aliases": {},
            "ephemeral": {
                "signal_name": (
                    "which signal triggered THIS process's drain, "
                    "reported on the summary line; the durable "
                    "consequence (the final snapshot with the full "
                    "pending queue) is what resume restores"),
                "_installed": (
                    "process-local handler-installation latch for "
                    "__exit__ symmetry; a fresh process re-installs "
                    "handlers on __enter__"),
                "_old": (
                    "the previous process's signal handlers, restored "
                    "on __exit__; meaningless outside this process"),
            },
        },
        "Supervisor": {
            "why": ("retry/backoff bookkeeping: the budget must not "
                    "silently reset mid-lineage"),
            "aliases": {},
            "ephemeral": {
                "run_fn": (
                    "caller-provided callable, rebound during "
                    "in-process resize-resume recovery (the resume "
                    "closure over the survivors); callables cannot "
                    "ride a snapshot — a restarted process passes a "
                    "fresh run_fn built from ITS resume path"),
                "attempts": (
                    "per-process attempt counter vs max_attempts: "
                    "the retry budget is DELIBERATELY per process "
                    "lineage (an operator-initiated restart gets a "
                    "fresh budget; in-process supervised retries "
                    "share one) — documented in the Supervisor "
                    "docstring and pinned by the retry-budget tests"),
                "recoveries": (
                    "(kind, action) history kept for tests and the "
                    "summary line of this process's attempts; the "
                    "durable record is the telemetry events file"),
            },
        },
    },
    "runtime/tune.py": {
        "OnlineAdapter": {
            "why": ("the --adapt knob state: values/streaks ride the "
                    "engine snapshot's adapt block (round 18), so a "
                    "resumed run continues the SAME walk instead of "
                    "re-warming from defaults"),
            "aliases": {},
            "ephemeral": {},
        },
    },
    "obs/slo.py": {
        "SloEvaluator": {
            "why": ("burn-rate alerting state: the evaluator is "
                    "re-based after registry replay at resume"),
            "aliases": {},
            "ephemeral": {
                "_burning": (
                    "per-SLO edge-trigger memory (was this key "
                    "burning at the last evaluation?) used only to "
                    "fire burn events on the False->True edge; after "
                    "resume the registry replay re-bases rates via "
                    "seed_base() and the next evaluation re-derives "
                    "the edge state within one window"),
                "_last_phase": (
                    "evaluation cursor re-seeded by seed_base() "
                    "after the resume path replays the registry "
                    "counters; persisting it separately could "
                    "contradict the replayed registry"),
                "_last_burning": (
                    "the previous evaluation's burning set, used "
                    "only for edge-triggered alert events; re-seeded "
                    "with _last_phase by seed_base() at resume"),
            },
        },
    },
    "obs/federation.py": {
        "FederatedMetrics": {
            "why": ("the cluster coordinator's merge state: the "
                    "federated registry and its per-process delta "
                    "bases must reset TOGETHER or counters double- "
                    "or under-count after a coordinator restart"),
            "aliases": {},
            "ephemeral": {
                "_prev": (
                    "per-process delta base (last cumulative dump) "
                    "paired with the coordinator's in-memory "
                    "federated registry: both reset together at "
                    "coordinator restart, so the next worker dump is "
                    "correctly folded in FULL (the fresh-restart "
                    "clamp); persisting _prev without the registry "
                    "would subtract an old base from a fresh "
                    "registry and under-count every counter"),
            },
        },
    },
    "runtime/checkpoint.py": {
        "CheckpointWriter": {
            "why": ("the background snapshot writer must never hold "
                    "durable state of its own: every queued job is "
                    "flushed before any resume/peek read (the "
                    "flush-before-read contract), so all four attrs "
                    "are in-process coordination only"),
            "aliases": {},
            "ephemeral": {
                "_q": ("pending write jobs; flush() drains the queue "
                       "before every snapshot READ, so no job ever "
                       "needs to survive the process"),
                "_busy": ("worker-liveness flag for flush()'s wait "
                          "predicate; in-process coordination only"),
                "_err": ("parked write error re-raised at the next "
                         "submit/flush call site; a process that "
                         "dies with a parked error already failed "
                         "loudly at the write site under PPLS_CHAOS "
                         "and fails the next flush otherwise"),
                "_closed": ("shutdown latch for the worker loop; a "
                            "fresh process starts a fresh writer"),
            },
        },
    },
}

# Function/method names whose string constants + kwarg names form a
# class's persistence surface (GL01's _SNAPSHOT_NAME_RE, widened with
# state/restore/payload for the host classes: OnlineAdapter.state()/
# restore() and the dispatcher's payload builders).
_GL12_SURFACE_RE = re.compile(
    r"identity|checkpoint|snapshot|resume|restore|state|payload",
    re.IGNORECASE)
# restore-side functions additionally contribute the attribute names
# they ASSIGN (``disp._cut_files = ...`` mentions no string key, but
# it IS the restore of that attr)
_GL12_RESTORE_RE = re.compile(r"resume|restore|load", re.IGNORECASE)

# in-place container mutators: ``self.X.append(...)`` mutates X just
# as surely as ``self.X = ...``
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "sort",
})


def _iter_shallow(root: ast.AST) -> Iterator[ast.AST]:
    """Walk a subtree WITHOUT descending into nested function/class
    definitions or lambdas: code inside a nested def does not execute
    where it is written (it runs when called, under whatever locks
    hold THERE), so lexical lock-region scans must not attribute it
    to the enclosing function."""
    stack = [root]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            stack.append(c)


def _unwrap_subscripts(node: ast.AST) -> ast.AST:
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` / ``self.X[...]`` -> ``X``; None otherwise."""
    node = _unwrap_subscripts(node)
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _flatten_targets(node: ast.AST) -> Iterator[ast.AST]:
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _flatten_targets(e)
    else:
        yield node


def _mutated_self_attrs(fn: ast.FunctionDef) -> Dict[str, int]:
    """``self.<attr>`` mutation sites in ``fn``: assignments (plain,
    augmented, annotated, tuple-unpacked, subscript stores) and
    in-place container mutator calls. -> {attr: first line}."""
    out: Dict[str, int] = {}

    def note(attr: Optional[str], line: int) -> None:
        if attr is not None and attr not in out:
            out[attr] = line

    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                for tt in _flatten_targets(t):
                    note(_self_attr(tt), n.lineno)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            note(_self_attr(n.target), n.lineno)
        elif isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATORS:
            note(_self_attr(n.func.value), n.lineno)
    return out


def _class_defs(mod: LintModule) -> Dict[str, ast.ClassDef]:
    """Every class in the module (nested ones included — the ingest
    and metrics servers define their HTTP handlers inside methods)."""
    return {n.name: n for n in ast.walk(mod.tree)
            if isinstance(n, ast.ClassDef)}


def _methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {f.name: f for f in cls.body
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _restored_attr_names(fn: ast.FunctionDef) -> Set[str]:
    """Attribute names a restore-side function rebuilds: stores
    through ANY object (``disp._cut_files = {...}``,
    ``eng._slot_req[slot] = req``) and in-place mutator calls
    (``eng._free.remove(slot)``)."""
    out: Set[str] = set()
    for n in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        elif isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATORS:
            targets = [n.func.value]
        for t in targets:
            for tt in _flatten_targets(t):
                tt = _unwrap_subscripts(tt)
                if isinstance(tt, ast.Attribute):
                    out.add(tt.attr)
    return out


def rule_gl12(modules: List[LintModule]) -> Iterator[Violation]:
    """GL12: every runtime-mutated attribute of a declared state class
    must appear on that class's snapshot/resume surface (string keys +
    kwarg names, GL01-style, plus restore-side attribute stores) or in
    the class's ephemeral allowlist with a reviewed reason.

    This generalizes GL01 (carry fields vs the checkpoint identity)
    to the host classes, the way rounds 16-22 needed it: the
    spillover counters (round 18) and the lease ledger (round 22)
    were both ``self.<attr>`` mutations whose spelling never reached
    any snapshot payload until a reviewer noticed. ``__init__`` is
    exempt — construction-time assignment is shape, not runtime
    mutation; what must ride the snapshot is state the RUN changes."""
    # the global persistence surface: runtime/checkpoint.py mentions
    # the generic payload keys (identity/totals/meta...) every
    # engine-side snapshot flows through (GL01 precedent)
    global_surface: Set[str] = set()
    # package-wide surface, consulted ONLY for declared alias targets:
    # some classes are persisted by ANOTHER module's snapshot (the
    # spillover totals ride the owning engine's totals block as
    # "spill_requests_total"), so an explicit reviewed alias may
    # resolve anywhere in the package's snapshot code — but a plain
    # attr spelling must still be covered class-locally, or the rule
    # would accept any string coincidence anywhere in the package.
    pkg_alias_surface: Set[str] = set()
    for mod in modules:
        if mod.path.endswith("runtime/checkpoint.py"):
            global_surface |= _string_surface(mod.tree)
        for n in ast.walk(mod.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _GL12_SURFACE_RE.search(n.name):
                pkg_alias_surface |= _string_surface(n)

    for mod in modules:
        decl = None
        for suffix, d in GL12_STATE_CLASSES.items():
            if mod.path.endswith(suffix):
                decl = d
                break
        if decl is None:
            continue
        classes = _class_defs(mod)
        module_funcs = dict(iter_functions(mod.tree))
        for cls_name, spec in decl.items():
            cls = classes.get(cls_name)
            if cls is None:
                continue
            methods = _methods(cls)
            # --- the class's persistence surface -------------------
            surface = set(global_surface)
            restore_assigned: Set[str] = set()
            contributing: List[ast.FunctionDef] = []
            for name, fn in methods.items():
                if _GL12_SURFACE_RE.search(name):
                    contributing.append(fn)
            for qn, fn in module_funcs.items():
                if _GL12_SURFACE_RE.search(qn) \
                        and not qn.startswith(tuple(
                            f"{c}." for c in classes)):
                    contributing.append(fn)
            # one hop: module-level helpers the surface functions call
            one_hop: Set[str] = set()
            for fn in contributing:
                one_hop |= _called_names(fn)
            for qn, fn in module_funcs.items():
                short = qn.split(".")[-1]
                if (qn in one_hop or short in one_hop) \
                        and fn not in contributing:
                    contributing.append(fn)
            for fn in contributing:
                surface |= _string_surface(fn)
                if _GL12_RESTORE_RE.search(fn.name):
                    restore_assigned |= _restored_attr_names(fn)
            surface |= restore_assigned
            surface |= {s.lstrip("_") for s in restore_assigned}
            # --- runtime mutation sites ----------------------------
            aliases: Dict[str, Tuple[str, ...]] = spec.get("aliases", {})
            ephemeral: Dict[str, str] = spec.get("ephemeral", {})
            mutated: Dict[str, int] = {}
            for name, fn in methods.items():
                if name == "__init__":
                    continue
                for attr, line in _mutated_self_attrs(fn).items():
                    mutated.setdefault(attr, line)
            for attr in sorted(mutated):
                if attr in ephemeral:
                    continue
                names = {attr, attr.lstrip("_")}
                alias_names = set(aliases.get(attr, ()))
                if (names | alias_names) & surface:
                    continue
                if alias_names & pkg_alias_surface:
                    continue
                names |= alias_names
                yield Violation(
                    code="GL12", path=mod.path, line=mutated[attr],
                    symbol=f"{cls_name}.{attr}",
                    message=(
                        f"{cls_name}.{attr} is mutated at runtime but "
                        f"absent from the class's snapshot/resume "
                        f"surface: no snapshot/restore code mentions "
                        f"{sorted(names)}, so a kill-and-resume "
                        f"silently resets it (the round-18 spillover-"
                        f"counter gap). Persist it, add a spelling "
                        f"alias, or declare it ephemeral in "
                        f"GL12_STATE_CLASSES with the reason it need "
                        f"not survive."))


# ---------------------------------------------------------------------------
# GL13 — lock-order + blocking-under-lock
# ---------------------------------------------------------------------------

# The declared lock vocabulary, per module: spelling -> logical lock
# identity. Spelling-based like GL11 (``with self._lock`` /
# ``with handle.lock():`` — the accessor counts), with the module
# scoping resolving the ambiguity of common names like ``_lock``.
# Every module that defines or acquires a serving-stack lock is
# listed; the serve loop's ``with handle.lock():`` in __main__.py maps
# to the SAME logical lock as ingest.py's ``self._lock``, which is
# what lets the lock-order graph see a cross-module cycle.
GL13_LOCK_DECLS: Dict[str, Dict[str, str]] = {
    "runtime/ingest.py": {"_lock": "EngineHandle._lock",
                          "lock": "EngineHandle._lock"},
    "__main__.py": {"lock": "EngineHandle._lock"},
    "runtime/checkpoint.py": {"_cv": "CheckpointWriter._cv",
                              "_WRITER_LOCK": "checkpoint._WRITER_LOCK"},
    "obs/registry.py": {"_lock": "MetricsRegistry._lock"},
    "obs/telemetry.py": {"_compile_lock": "telemetry._compile_lock",
                         "_default_lock": "telemetry._default_lock"},
    "runtime/faults.py": {"_lock": "faults._lock"},
}

# Declared engine-RPC call names: ``eng.step()`` is a full device
# phase (the round-19 hang wedged exactly here), ``readline()`` on a
# worker pipe is the coordinator's blocking RPC read. Reviewed
# additions only — each carries its reason.
GL13_RPC_CALLS: Dict[str, str] = {
    "step": ("a StreamEngine/dispatcher step() is a whole device "
             "phase (possibly hung hardware — the round-19 deadlock "
             "was an injected hang inside step() under the handle "
             "lock)"),
    "readline": ("a blocking pipe read from a cluster worker "
                 "subprocess; a dead worker never answers"),
}


def _lock_of_with(item: ast.withitem,
                  decls: Dict[str, str]) -> Optional[str]:
    """Logical lock id acquired by a with-item, per the module's
    declared spellings (``self._lock``, ``handle.lock()``, a bare
    ``_lock`` global)."""
    for n in ast.walk(item.context_expr):
        if isinstance(n, ast.Attribute) and n.attr in decls:
            return decls[n.attr]
        if isinstance(n, ast.Name) and n.id in decls:
            return decls[n.id]
    return None


def _blocking_name(call: ast.Call) -> Optional[str]:
    """Name of the blocking operation a call performs, or None.

    Heuristics tuned to stay quiet on the safe spellings: ``.get()``
    with positional args is ``dict.get``; ``.join(x)`` with args is
    ``str.join``/``os.path.join``/``Thread.join(timeout)``; any
    ``timeout=`` kwarg bounds the wait and is accepted."""
    has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
    f = call.func
    if isinstance(f, ast.Attribute):
        a = f.attr
        if a in ("accept", "recv", "recvfrom", "serve_forever"):
            return a
        if a in ("wait", "communicate") and not call.args \
                and not has_timeout:
            return a
        if a in ("join", "get") and not call.args and not has_timeout:
            return a
        if a in GL13_RPC_CALLS:
            return a
    if _dotted(f) == "time.sleep":
        return "time.sleep"
    return None


def _all_defs(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """name -> node for EVERY def in the module, nested ones included
    (the serve loop is a closure; handler methods live in nested
    classes). First definition wins on name collisions."""
    out: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(n.name, n)
    return out


def _class_method_index(mod: LintModule
                        ) -> Dict[str, Dict[str, ast.FunctionDef]]:
    return {name: _methods(cls)
            for name, cls in _class_defs(mod).items()}


class _CallGraph:
    """Extended intra-package call resolution shared by GL13/GL14:
    the GL03/GL06 resolver (imports, module attrs, functools.partial)
    plus ``self.method()`` edges, unique-in-module method-name edges,
    and Thread/HTTPServer handler targets (GL14 entry discovery)."""

    def __init__(self, modules: List[LintModule]):
        self.modules = modules
        self.by_key = {m.modkey: m for m in modules}
        self.index = _build_call_index(modules)
        self.defs = {m.modkey: _all_defs(m.tree) for m in modules}
        self.classes = {m.modkey: _class_method_index(m)
                        for m in modules}
        # method name -> owning classes, per module (for the
        # unique-name fallback)
        self.owners: Dict[str, Dict[str, List[str]]] = {}
        for m in modules:
            d: Dict[str, List[str]] = {}
            for cname, ms in self.classes[m.modkey].items():
                for mname in ms:
                    d.setdefault(mname, []).append(cname)
            self.owners[m.modkey] = d

    def lookup(self, modkey: str, qual: str
               ) -> Optional[ast.FunctionDef]:
        if "." in qual:
            cname, mname = qual.split(".", 1)
            got = self.classes.get(modkey, {}).get(cname, {}) \
                .get(mname)
            if got is not None:
                return got
        return (self.index.get(modkey, {}).get(qual)
                or self.defs.get(modkey, {}).get(qual))

    def callees(self, modkey: str, region: ast.AST,
                self_cls: Optional[str], shallow: bool = False
                ) -> List[Tuple[str, str]]:
        """(modkey, qualname) of every resolvable callee in the
        region. Thread targets are NOT followed here — a spawned
        thread does not run under the spawner's locks (GL14 handles
        thread entries separately). ``shallow`` skips nested defs
        (lock-region scans: a closure's body runs when called, not
        where defined)."""
        mod = self.by_key[modkey]
        out: List[Tuple[str, str]] = []
        for n in (_iter_shallow(region) if shallow
                  else ast.walk(region)):
            if not isinstance(n, ast.Call):
                continue
            r = _resolve_callee(mod, n, self.index)
            if r is not None:
                out.append(r)
                continue
            f = n.func
            if isinstance(f, ast.Attribute):
                if isinstance(f.value, ast.Name) \
                        and f.value.id == "self" and self_cls \
                        and f.attr in self.classes[modkey].get(
                            self_cls, {}):
                    out.append((modkey, f"{self_cls}.{f.attr}"))
                    continue
                own = self.owners[modkey].get(f.attr, [])
                if len(own) == 1:
                    out.append((modkey, f"{own[0]}.{f.attr}"))
        return out

    def thread_entries(self, modkey: str) -> List[Tuple[str, str]]:
        """Thread-entry functions DEFINED in the module:
        ``threading.Thread(target=...)`` targets and ``do_*`` methods
        of ``BaseHTTPRequestHandler`` subclasses (nested classes
        included — both servers define their handler inline)."""
        mod = self.by_key[modkey]
        out: List[Tuple[str, str]] = []
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call) \
                    and _dotted(n.func).split(".")[-1] == "Thread":
                for kw in n.keywords:
                    if kw.arg != "target":
                        continue
                    t = kw.value
                    if isinstance(t, ast.Name) \
                            and t.id in self.defs[modkey]:
                        out.append((modkey, t.id))
                    elif isinstance(t, ast.Attribute):
                        own = self.owners[modkey].get(t.attr, [])
                        if len(own) == 1:
                            out.append((modkey,
                                        f"{own[0]}.{t.attr}"))
            elif isinstance(n, ast.ClassDef) and any(
                    "BaseHTTPRequestHandler" in _dotted(b)
                    or _dotted(b).endswith("Handler")
                    for b in n.bases):
                for mname in _methods(n):
                    if mname.startswith("do_"):
                        out.append((modkey, f"{n.name}.{mname}"))
        return out


def _enclosing_functions(tree: ast.Module
                         ) -> List[Tuple[str, Optional[str],
                                         ast.FunctionDef]]:
    """(display qualname, enclosing class or None, node) for every
    def, nested ones included."""
    out: List[Tuple[str, Optional[str], ast.FunctionDef]] = []

    def walk(node: ast.AST, prefix: str, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                out.append((qn, cls, child))
                walk(child, f"{qn}.", cls)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{child.name}.", child.name)
            else:
                walk(child, prefix, cls)

    walk(tree, "", None)
    return out


def rule_gl13(modules: List[LintModule]) -> Iterator[Violation]:
    """GL13: lock-acquisition cycles, and blocking operations
    reachable while a declared lock is held.

    From every ``with <declared lock>:`` site, the body and every
    intra-package function it (transitively) calls are scanned for
    (a) blocking operations — socket accept/recv, untimed ``wait``/
    ``join``/``get``/``communicate``, ``time.sleep``, declared
    engine-RPC names like ``step`` — and (b) acquisitions of OTHER
    declared locks, which become edges of the lock-order graph; any
    cycle in that graph flags. A ``cv.wait()`` ON the held condition
    is exempt (the idiom releases the lock while waiting). This is
    the round-19 deadlock shape as a rule: ``eng.step()`` under the
    handle lock wedged one attempt, and every retry then blocked
    forever on ``with handle.lock():``."""
    graph = _CallGraph(modules)
    decls_by_mod: Dict[str, Dict[str, str]] = {}
    for mod in modules:
        for suffix, d in GL13_LOCK_DECLS.items():
            if mod.path.endswith(suffix):
                decls_by_mod[mod.modkey] = d
                break
    if not decls_by_mod:
        return
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    acq_site: Dict[str, Tuple[str, int]] = {}
    reported: Set[Tuple[str, str, str]] = set()
    out: List[Violation] = []

    def wait_on_held(call: ast.Call, lock_id: str,
                     decls: Dict[str, str]) -> bool:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "wait"):
            return False
        for n in ast.walk(f.value):
            spelled = (n.attr if isinstance(n, ast.Attribute)
                       else n.id if isinstance(n, ast.Name) else None)
            if spelled is not None \
                    and decls.get(spelled) == lock_id:
                return True
        return False

    def scan(modkey: str, qual: str, self_cls: Optional[str],
             region: ast.AST, lock_id: str, origin_decls,
             visited: Set[Tuple[str, str]], depth: int) -> None:
        mod = graph.by_key[modkey]
        decls = dict(decls_by_mod.get(modkey, {}))
        decls.update({k: v for k, v in origin_decls.items()
                      if k not in decls})
        # (a) blocking operations, lexically in this region (nested
        # defs excluded — they run when called, and calls are edges)
        for n in _iter_shallow(region):
            if isinstance(n, ast.Call):
                op = _blocking_name(n)
                if op is not None \
                        and not wait_on_held(n, lock_id, decls):
                    key = (lock_id, f"{qual}:{op}", mod.path)
                    if key not in reported:
                        reported.add(key)
                        out.append(Violation(
                            code="GL13", path=mod.path,
                            line=n.lineno, symbol=f"{qual}:{op}",
                            message=(
                                f"{qual} performs the blocking "
                                f"operation {op!r} while "
                                f"{lock_id} is held: a hang here "
                                f"wedges every other thread on the "
                                f"lock (the round-19 EngineHandle "
                                f"deadlock burned the whole retry "
                                f"budget this way). Move the "
                                f"blocking call outside the lock, "
                                f"bound it with a timeout, or "
                                f"allowlist with the reason the "
                                f"hold is safe.")))
            # (b) nested acquisitions -> lock-order edges
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    other = _lock_of_with(item, decls)
                    if other is not None and other != lock_id:
                        edges.setdefault((lock_id, other),
                                         (mod.path, n.lineno, qual))
        # (c) transitive callees run under the lock too
        if depth >= 8:
            return
        for ck, cq in graph.callees(modkey, region, self_cls,
                                    shallow=True):
            if (ck, cq) in visited:
                continue
            visited.add((ck, cq))
            fn = graph.lookup(ck, cq)
            if fn is None:
                continue
            c_cls = cq.split(".", 1)[0] if "." in cq else None
            scan(ck, cq, c_cls, fn, lock_id, origin_decls,
                 visited, depth + 1)

    for mod in modules:
        decls = decls_by_mod.get(mod.modkey)
        if decls is None:
            continue
        for qual, cls, fn in _enclosing_functions(mod.tree):
            for n in _iter_shallow(fn):
                if not isinstance(n, (ast.With, ast.AsyncWith)):
                    continue
                for item in n.items:
                    lock_id = _lock_of_with(item, decls)
                    if lock_id is None:
                        continue
                    acq_site.setdefault(lock_id,
                                        (mod.path, n.lineno))
                    body = ast.Module(body=list(n.body),
                                      type_ignores=[])
                    scan(mod.modkey, qual, cls, body, lock_id,
                         decls, {(mod.modkey, qual)}, 0)

    # cycle detection over the acquisition graph
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(node: str, stack: List[str], on_stack: Set[str]) -> None:
        for nxt in adj.get(node, ()):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                # canonical rotation: start at the min element
                core = cyc[:-1]
                i = core.index(min(core))
                rot = tuple(core[i:] + core[:i])
                seen_cycles.add(rot)
            else:
                on_stack.add(nxt)
                dfs(nxt, stack + [nxt], on_stack)
                on_stack.discard(nxt)

    for start in sorted(adj):
        dfs(start, [start], {start})
    for rot in sorted(seen_cycles):
        first = rot[0]
        path, line = acq_site.get(first, ("", 0))
        chain = "->".join(rot + (rot[0],))
        out.append(Violation(
            code="GL13", path=path, line=line,
            symbol=f"cycle:{chain}",
            message=(
                f"lock-acquisition cycle {chain}: two threads "
                f"taking these locks in opposite orders deadlock. "
                f"Impose a single acquisition order (or collapse "
                f"to one lock).")))
    yield from out


# ---------------------------------------------------------------------------
# GL14 — thread-shared-state audit
# ---------------------------------------------------------------------------

# Attributes that are shared across threads BY DESIGN without a lock:
# immutable-after-publication or atomic (a threading.Event, a single
# reference assignment read once). Reviewed declarations with reasons,
# like every other allowlist in this package.
GL14_SHARED_OK: Dict[str, Dict[str, str]] = {
    "runtime/guard.py": {
        "_flag": ("threading.Event is internally locked; set() from "
                  "the signal handler and is_set() from the serve "
                  "loop are the documented atomic pair"),
    },
}


def rule_gl14(modules: List[LintModule]) -> Iterator[Violation]:
    """GL14: an attribute written at runtime and touched both from a
    thread-entry function (Thread target, HTTP handler ``do_*``,
    the checkpoint-writer worker) and from main-side code must be in
    the module's GL11 guarded set or a declared immutable/atomic
    allowlist.

    GL11 enforces the lexical with-lock discipline on attrs ALREADY
    declared shared; this rule finds the attrs that SHOULD be
    declared: state a new thread quietly started sharing (the PR-10
    ingest race began exactly this way — ``_eng`` was cross-thread
    long before any lock map said so)."""
    graph = _CallGraph(modules)
    for mod in modules:
        entries = graph.thread_entries(mod.modkey)
        if not entries:
            continue
        # thread-reachable closure (package-wide BFS)
        thread_reach: Set[Tuple[str, str]] = set()
        queue = list(entries)
        while queue:
            key = queue.pop()
            if key in thread_reach:
                continue
            thread_reach.add(key)
            mk, qn = key
            fn = graph.lookup(mk, qn)
            if fn is None:
                continue
            cls = qn.split(".", 1)[0] if "." in qn else None
            for c in graph.callees(mk, fn, cls):
                if c not in thread_reach:
                    queue.append(c)
        local_thread = {qn for mk, qn in thread_reach
                        if mk == mod.modkey}
        # guarded/allowlisted attrs for this module
        guarded: Set[str] = set()
        unlocked_ok: Set[str] = set()
        for suffix, e in GL11_LOCK_MAP.items():
            if mod.path.endswith(suffix):
                guarded |= set(e["guarded"])
                unlocked_ok |= set(e.get("unlocked_ok", ()))
        shared_ok: Dict[str, str] = {}
        for suffix, d in GL14_SHARED_OK.items():
            if mod.path.endswith(suffix):
                shared_ok = d
                break
        # per-class attr touch/write maps
        for cls_name, cls in _class_defs(mod).items():
            touches: Dict[str, Set[str]] = {}
            writes: Dict[str, int] = {}
            for mname, fn in _methods(cls).items():
                qual = f"{cls_name}.{mname}"
                for n in ast.walk(fn):
                    a = _self_attr(n) if isinstance(
                        n, (ast.Attribute, ast.Subscript)) else None
                    if a is not None:
                        touches.setdefault(a, set()).add(qual)
                if mname == "__init__" or mname in unlocked_ok:
                    continue
                for attr, line in _mutated_self_attrs(fn).items():
                    writes.setdefault(attr, line)
            for attr in sorted(writes):
                users = touches.get(attr, set())
                t_side = {q for q in users if q in local_thread}
                m_side = users - t_side
                if not t_side or not m_side:
                    continue
                if attr in guarded or attr in shared_ok:
                    continue
                yield Violation(
                    code="GL14", path=mod.path, line=writes[attr],
                    symbol=f"{cls_name}.{attr}",
                    message=(
                        f"{cls_name}.{attr} is written at runtime and "
                        f"touched from both a thread entry "
                        f"({', '.join(sorted(t_side))}) and main-side "
                        f"code ({', '.join(sorted(m_side))}) but is "
                        f"neither in the module's GL11 guarded set "
                        f"nor declared immutable/atomic in "
                        f"GL14_SHARED_OK: this is un-declared "
                        f"cross-thread mutable state (the PR-10 race "
                        f"started exactly like this). Guard it with "
                        f"the module's lock (and add it to "
                        f"GL11_LOCK_MAP), or declare why it is safe "
                        f"bare."))


# ---------------------------------------------------------------------------

RUNTIME_RULES = (rule_gl12, rule_gl13, rule_gl14)
RUNTIME_CODES = ("GL12", "GL13", "GL14")


def run_runtime(target: str) -> List[Violation]:
    """The ``--runtime`` tier entry: the three host-runtime rules over
    the target package, with the shared pragma handling (run_lint
    applies ``# graftlint: GLxx`` suppression and sorting)."""
    return run_lint(target, rules=RUNTIME_RULES)
