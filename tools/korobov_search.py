"""Korobov generating-vector search — provenance for ``qmc.KOROBOV_A``.

VERDICT r3 #9 / r4 #7: the QMC engine's lattice quality rested on three
hardcoded generators with no reproduction script. This is that script.

Criterion: the standard P_2 worst-case error of the rank-1 Korobov
lattice z = (1, a, a^2, ..., a^{d-1}) mod N in the weighted Korobov
space with product weights gamma_j = 2^-j (j = 1..d, decaying —
earlier coordinates matter more, matching how the Genz families
weight their first coordinates through the a-vector draw):

    P_2(a, N) = -1 + (1/N) * sum_k prod_j (1 + gamma_j * w({k z_j / N}))
    w(x) = 2 pi^2 (x^2 - x + 1/6)          # = 2 pi^2 B_2(x)

(B_2 the Bernoulli polynomial; sum_k w({k z/N}) telescopes the alpha=2
Korobov-space worst-case sum.) Candidates: K odd values drawn uniformly
from (1, N/2) with a fixed seed, the classic Korobov restriction
(a and N-a generate mirror-image lattices, so half the range suffices),
PLUS the incumbent ``qmc.KOROBOV_A`` values so a re-run can only
confirm or improve the table.

Run (CPU, ~1 min for the three shipped sizes; 2^22 adds ~2 min):

    python tools/korobov_search.py            # shipped sizes
    python tools/korobov_search.py --full     # + 2^22

and paste the printed table into ``ppls_tpu/parallel/qmc.py``.

Validation (round 5, real v5e): the shipped table's N=2^22 generator
integrates all six 8D Genz families to worst relative error 3.8e-4
(8 random shifts, seed 17; oscillatory is the worst case — stderr
5.5e-6, consistent with lattice bias, not shift noise), well inside
the bench gate of 1e-2; N=2^18 measures 3.4e-4 with the new table vs
1.1e-3 with the superseded round-2 constants on the same suite.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

D = 8
N_CANDIDATES = 256
SEED = 42
GAMMA = 0.5 ** np.arange(1, D + 1)          # product weights 2^-j


def p2_criterion(a: int, n: int, d: int = D,
                 gamma: np.ndarray = GAMMA,
                 _k_cache: dict = {}) -> float:
    """P_2 worst-case error (squared, up to the constant -1 term) of the
    Korobov lattice with generator a, vectorized over all N points.

    The k*z_j mod N reduction runs in f64, not int64: with k < N <= 2^22
    and z_j < N the product is < 2^44 — exact in f64 — and float
    floor-division is ~8x faster than numpy's int64 %, which made the
    naive version time out at N=2^22 on this single-core host. A
    where-correction absorbs the at-most-one-off floor rounding.
    """
    if n not in _k_cache:
        _k_cache[n] = np.arange(n, dtype=np.float64)
    k = _k_cache[n]
    nf = float(n)
    prod = np.ones(n, dtype=np.float64)
    zj = 1
    for j in range(d):
        y = k * float(zj)                    # exact: < 2^44
        r = y - np.floor(y / nf) * nf
        r = np.where(r >= nf, r - nf, r)
        r = np.where(r < 0.0, r + nf, r)
        frac = r / nf
        w = 2.0 * np.pi ** 2 * (frac * frac - frac + 1.0 / 6.0)
        prod *= 1.0 + gamma[j] * w
        zj = (zj * a) % n
    return float(prod.mean() - 1.0)


def search(n: int, extra_candidates=(), n_candidates: int = N_CANDIDATES,
           seed: int = SEED):
    """Best generator among seeded odd candidates + any incumbents."""
    rng = np.random.default_rng(seed)
    cand = set(int(c) for c in extra_candidates)
    while len(cand) < n_candidates:
        a = int(rng.integers(3, n // 2))
        cand.add(a | 1)                      # odd
    scored = sorted((p2_criterion(a, n), a) for a in sorted(cand))
    return scored[0][1], scored[0][0], scored


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="also search N=2^22 (~2 min extra)")
    ap.add_argument("--sizes", type=int, nargs="*", default=None,
                    help="explicit log2 sizes (default: 16 18 20 [22])")
    args = ap.parse_args()

    from ppls_tpu.parallel.qmc import KOROBOV_A

    log2s = args.sizes or ([16, 18, 20, 22] if args.full else [16, 18, 20])
    table = {}
    for lg in log2s:
        n = 1 << lg
        incumbent = KOROBOV_A.get(n)
        best_a, best_p2, scored = search(
            n, extra_candidates=[incumbent] if incumbent else [])
        inc_p2 = p2_criterion(incumbent, n) if incumbent else None
        table[n] = best_a
        status = ("MATCHES incumbent" if incumbent == best_a else
                  f"incumbent {incumbent} (P2={inc_p2:.3e}) superseded"
                  if incumbent else "new size")
        print(f"N=2^{lg}: a={best_a}  P2={best_p2:.6e}  [{status}; "
              f"median candidate P2={scored[len(scored)//2][0]:.3e}]",
              flush=True)
    print("\nKOROBOV_A = {")
    for n in sorted(table):
        print(f"    1 << {n.bit_length() - 1}: {table[n]},")
    print("}")


if __name__ == "__main__":
    main()
