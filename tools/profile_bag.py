"""Microbenchmark of the bag-engine loop-body components on the live
backend (run on the real TPU to see what an iteration actually costs).

Each component runs K times inside ONE jitted fori_loop, with the
component's *inputs derived from the loop carry* and its *output folded
back into the carry* — a true loop-carried data dependence, so XLA can
neither DCE the component nor hoist it out of the loop (a plain `x * 0`
sink gets constant-folded away entirely; measured 0.5 us/iter for
everything, i.e. nothing ran).

Usage: python tools/profile_bag.py [K]
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

jax.config.update("jax_enable_x64", True)

CHUNK = 1 << 16
CAP = 1 << 22
M = 128
K = int(sys.argv[1]) if len(sys.argv) > 1 else 100


def bench(name, run, *args):
    f = jax.jit(run)
    out = f(*args)          # compile + warmup
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = f(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / K
    print(f"{name:45s} {dt*1e6:9.1f} us/iter")
    return dt


def scalar_loop(body):
    """K iterations; body(carry, *args) -> new f64 carry, inputs perturbed
    by the carry so each iteration truly depends on the previous."""
    def run(*args):
        def b(i, c):
            return body(c, *args)
        return lax.fori_loop(0, K, b, jnp.float64(1.0))
    return run


def main():
    rng = np.random.default_rng(0)
    l = jnp.asarray(rng.uniform(1e-4, 0.5, CHUNK))
    r = l + 1e-6
    fam = jnp.asarray(rng.integers(0, M, CHUNK), dtype=jnp.int32)
    theta = jnp.asarray(1.0 + np.arange(M) / M)
    bag_l = jnp.asarray(rng.uniform(1e-4, 1.0, CAP + 2 * CHUNK))
    leaf = jnp.asarray(rng.uniform(0, 1e-9, CHUNK))

    def f_eval(x, th):
        return jnp.sin(th / x)

    def wob(c):
        """tiny carry-dependent perturbation, keeps values in range"""
        return (c % jnp.float64(3.0)) * 1e-9

    # 1. integrand eval: 3 points + trapezoid arithmetic (f64)
    def eval_body(c, l, r, th):
        ll = l + wob(c)
        m = (ll + r) * 0.5
        fl, fm, fr = f_eval(ll, th), f_eval(m, th), f_eval(r, th)
        h = r - ll
        lr = (fl + fr) * h * 0.5
        two = (fl + fm) * h * 0.25 + (fm + fr) * h * 0.25
        return c + jnp.sum(jnp.where(jnp.abs(two - lr) > 1e-10, two, lr))

    bench("eval 3pt+trap, scalar theta (f64)",
          scalar_loop(eval_body), l, r, jnp.float64(1.5))
    th_vec = theta[fam]
    bench("eval 3pt+trap, vector theta (f64)",
          scalar_loop(eval_body), l, r, th_vec)

    def eval32_body(c, l, r, th):
        ll = l + wob(c).astype(jnp.float32)
        m = (ll + r) * 0.5
        fl, fm, fr = f_eval(ll, th), f_eval(m, th), f_eval(r, th)
        h = r - ll
        lr = (fl + fr) * h * 0.5
        two = (fl + fm) * h * 0.25 + (fm + fr) * h * 0.25
        return c + jnp.sum(jnp.where(jnp.abs(two - lr) > 1e-7, two, lr))

    bench("eval 3pt+trap, vector theta (f32)",
          scalar_loop(eval32_body), l.astype(jnp.float32),
          r.astype(jnp.float32), th_vec.astype(jnp.float32))

    # 2. the theta[fam] gather alone (indices depend on carry)
    def gather_body(c, theta, fam):
        idx = (fam + (c.astype(jnp.int32) & 1)) % M
        return c + theta[idx].sum() * 1e-12

    bench("theta[fam] gather (128-table, 65536)",
          scalar_loop(gather_body), theta, fam)

    # 3. 4-operand stable sort by 1-bit key (operands depend on carry)
    def sort_body(c, l, r, fam):
        ll = l + wob(c)
        key = (ll > 0.25).astype(jnp.int32)
        _, sl, sr, sfam = lax.sort((key, ll, r, fam), dimension=0,
                                   is_stable=True, num_keys=1)
        return c + sl[0] + sr[CHUNK - 1] + sfam[0] * 1e-12

    bench("4-op stable sort (65536)", scalar_loop(sort_body), l, r, fam)

    def sort2_body(c, l, r, fam):
        ll = l + wob(c)
        key = (ll > 0.25).astype(jnp.int32)
        _, sl, sr = lax.sort((key, ll, r), dimension=0,
                             is_stable=True, num_keys=1)
        return c + sl[0] + sr[CHUNK - 1]

    bench("3-op stable sort (65536)", scalar_loop(sort2_body), l, r, fam)

    # 4. family reduce variants (leaf depends on carry)
    def famred_mask(c, fam, leaf):
        lf = leaf + wob(c)
        ids = jnp.arange(M, dtype=jnp.int32)
        seg = jnp.where(fam[None, :] == ids[:, None], lf[None, :], 0.0).sum(axis=1)
        return c + seg.sum() * 1e-12

    bench("family reduce: mask (128x65536 f64)",
          scalar_loop(famred_mask), fam, leaf)

    def famred_mm(c, fam, leaf):
        lf = leaf + wob(c)
        hi = lf.astype(jnp.float32)
        lo = (lf - hi.astype(jnp.float64)).astype(jnp.float32)
        oh = jax.nn.one_hot(fam, M, dtype=jnp.float32)
        s = (hi @ oh).astype(jnp.float64) + (lo @ oh).astype(jnp.float64)
        return c + s.sum() * 1e-12

    bench("family reduce: 2xf32 one-hot matmul",
          scalar_loop(famred_mm), fam, leaf)

    def famred_scatter(c, fam, leaf):
        lf = leaf + wob(c)
        acc = jnp.zeros(M, dtype=jnp.float64).at[fam].add(lf)
        return c + acc.sum() * 1e-12

    bench("family reduce: scatter-add", scalar_loop(famred_scatter), fam, leaf)

    def famred_mm64(c, fam, leaf):
        lf = leaf + wob(c)
        oh = jax.nn.one_hot(fam, M, dtype=jnp.float64)
        return c + (lf @ oh).sum() * 1e-12

    bench("family reduce: f64 one-hot matmul",
          scalar_loop(famred_mm64), fam, leaf)

    # 5. dynamic_slice pops from the big bag at a carry-dependent offset
    def pop_body(c, bag):
        start = (c.astype(jnp.int32) * 2654435761 % CAP) & (CAP - 1)
        a = lax.dynamic_slice(bag, (start,), (CHUNK,))
        b = lax.dynamic_slice(bag, (start,), (CHUNK,))
        d = lax.dynamic_slice(bag, (start,), (CHUNK,))
        return c + a[0] + b[1] + d[2]

    bench("3x dynamic_slice pop (4M bag)", scalar_loop(pop_body), bag_l)

    # 6. dynamic_update_slice push: carries the big bag itself
    ch = jnp.concatenate([l, r])

    def push1(bag, ch):
        def b(i, carry):
            bag2, c = carry
            start = (c.astype(jnp.int32) * 2654435761 % CAP) & (CAP - 1)
            bag2 = lax.dynamic_update_slice(bag2, ch + wob(c), (start,))
            return (bag2, c + bag2[0])
        out = lax.fori_loop(0, K, b, (bag, jnp.float64(1.0)))
        return out[1]

    bench("1x dyn_update_slice push (131072 into 4M)", push1, bag_l, ch)

    def push3(b1, b2, b3, ch):
        def b(i, carry):
            x1, x2, x3, c = carry
            start = (c.astype(jnp.int32) * 2654435761 % CAP) & (CAP - 1)
            x1 = lax.dynamic_update_slice(x1, ch + wob(c), (start,))
            x2 = lax.dynamic_update_slice(x2, ch + wob(c), (start,))
            x3 = lax.dynamic_update_slice(x3, ch + wob(c), (start,))
            return (x1, x2, x3, c + x1[0] + x2[0] + x3[0])
        out = lax.fori_loop(0, K, b, (b1, b2, b3, jnp.float64(1.0)))
        return out[3]

    bench("3x dyn_update_slice push", push3, bag_l, bag_l + 1, bag_l + 2, ch)


if __name__ == "__main__":
    main()
