"""Walker kernel ceiling measurement.

Measures the Pallas segment kernel's raw lane-step rate with ONE device
dispatch around K restarted segments — the only reliable way to time it
on this host: per-launch overhead is ~0.07 ms and the tunneled device
adds ~100 ms per sync, so K separate launches measure dispatch, not
compute (see the round-3 ceiling analysis in the git log).

Run: ``python tools/profile_walker.py`` (real TPU). Prints both the
single-dispatch number and the SLOPE ceiling.

ROUND-5 CORRECTION: the single-dispatch wall time here includes ONE
tunnel RTT (~120-220 ms on this rig), which at the default workload is
comparable to the compute itself — the round-3 "1.5 G lane-steps/s"
ceiling derived from this tool was RTT-polluted. Measuring the same
kernel by two-point slope (64 vs 512 outer restarts, differencing
cancels the constant overhead) gives ~4.55 G lane-steps/s at 2^14
lanes on v5e — i.e. the kernel is ~3x faster than round 3 believed,
and the engine's lane_efficiency (structural max ~2/3 for the
trapezoid DFS: ~1.5 steps per task) is the honest utilization number
to optimize. ``kernel_ceiling_slope`` (round 6) implements exactly
that two-point method and is what ``bench.py`` re-profiles each round
for the JSON's ``kernel_wall_frac``/``kernel_ceiling_frac`` headroom
pair — always quote the slope number, never the single-dispatch one.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ppls_tpu.models.integrands import get_family_ds
from ppls_tpu.parallel.walker import WalkState, make_walk_kernel


def kernel_ceiling(lanes: int = 1 << 15, seg_iters: int = 256,
                   outer: int = 32, eps: float = 1e-10):
    """All lanes walk deep subtrees forever (restarted each segment)."""
    fds = get_family_ds("sin_recip_scaled")
    rows = lanes // 128
    rng = np.random.default_rng(0)
    z = np.zeros((rows, 128), np.float32)
    a64 = 1e-4 * (1.0 + 30.0 * rng.random((rows, 128)))
    w64 = np.full((rows, 128), 2e-6)
    th64 = 1.0 + rng.random((rows, 128))

    def ds(x):
        hi = x.astype(np.float32)
        lo = (x - hi.astype(np.float64)).astype(np.float32)
        return jnp.array(hi), jnp.array(lo)

    a_h, a_l = ds(a64)
    w_h, w_l = ds(w64)
    th_h, th_l = ds(th64)
    fl = np.sin(th64 / a64).astype(np.float32)
    fr = np.sin(th64 / (a64 + w64)).astype(np.float32)
    zi = jnp.zeros((rows, 128), jnp.int32)
    zj = jnp.array(z)
    s0 = WalkState(
        a_h=a_h, a_l=a_l, w_h=w_h, w_l=w_l, th_h=th_h, th_l=th_l,
        fl_h=jnp.array(fl), fl_l=zj,
        fr_h=jnp.array(fr), fr_l=zj,
        fm_h=zj, fm_l=zj, fq_h=zj, fq_l=zj,
        acc_h=zj, acc_l=zj,
        i=zi, d=zi, base_d=zi, fam=zi, flags=zi,
        tasks=zi, splits=zi, maxd=zi)

    seg = make_walk_kernel(fds, eps, seg_iters, interpret=False)

    @jax.jit
    def many(s_init):
        def body(_, s):
            out = seg(s)
            # restart the walk so no lane ever parks
            return out._replace(i=s_init.i, d=s_init.d,
                                flags=s_init.flags,
                                fl_h=s_init.fl_h, fl_l=s_init.fl_l,
                                fr_h=s_init.fr_h, fr_l=s_init.fr_l)
        return lax.fori_loop(0, outer, body, s_init)

    out = many(s0)
    int(jax.device_get(jnp.sum(out.tasks)))   # warm + true sync
    t0 = time.perf_counter()
    out = many(s0)
    # time through a HOST DATA PULL: on this tunneled device
    # block_until_ready sometimes acknowledges before execution
    # completes (measured "740 G lane-steps/s"), so only a value
    # dependency gives a true completion time.
    tasks = int(jax.device_get(jnp.sum(out.tasks)))
    dt = time.perf_counter() - t0
    steps = outer * seg_iters * lanes
    return {
        "lane_steps_per_sec": steps / dt,
        "tasks_per_sec_full_occupancy": tasks / dt,
        "wall_s": dt,
        "lanes": lanes,
        "seg_iters": seg_iters,
    }


def kernel_ceiling_slope(lanes: int = 1 << 14, seg_iters: int = 256,
                         outer_lo: int = 64, outer_hi: int = 512,
                         eps: float = 1e-10):
    """Two-point-slope kernel ceiling (the round-5 methodology — the
    number to quote): time the SAME restarted-segment program at two
    outer-restart counts and difference, so every constant cost (the
    tunnel RTT, dispatch, the warmup sync) cancels:

        ceiling = (steps_hi - steps_lo) / (wall_hi - wall_lo)

    Defaults profile the bench's lanes=2^14 operating point. This is
    what ``bench.py`` runs same-day for its ``kernel_wall_frac`` /
    ``kernel_ceiling_frac`` headroom fields.
    """
    lo = kernel_ceiling(lanes=lanes, seg_iters=seg_iters,
                        outer=outer_lo, eps=eps)
    hi = kernel_ceiling(lanes=lanes, seg_iters=seg_iters,
                        outer=outer_hi, eps=eps)
    d_steps = (outer_hi - outer_lo) * seg_iters * lanes
    d_wall = hi["wall_s"] - lo["wall_s"]
    if d_wall <= 0:
        raise RuntimeError(
            f"non-positive slope window ({d_wall:.4f} s between "
            f"outer={outer_lo} and outer={outer_hi}); rerun — a "
            f"contended host or a tunnel hiccup inverted the timings")
    return {
        "lane_steps_per_sec": d_steps / d_wall,
        "method": "two-point-slope",
        "outer_lo": outer_lo,
        "outer_hi": outer_hi,
        "wall_lo_s": lo["wall_s"],
        "wall_hi_s": hi["wall_s"],
        "lanes": lanes,
        "seg_iters": seg_iters,
        # the RTT-polluted single-dispatch rates, kept for comparison
        "single_dispatch_lo": lo["lane_steps_per_sec"],
        "single_dispatch_hi": hi["lane_steps_per_sec"],
    }


def dd_kernel_ceiling_slope(lanes: int = 1 << 12, **kw):
    """Per-chip kernel ceiling at the DEMAND-DRIVEN engine's operating
    point (dd default lanes=2^12 per chip vs the single-chip
    flagship's 2^14): the dd leg's kernel_wall_frac/kernel_ceiling_frac
    must rate against the ceiling of the lane count it actually runs,
    or the headroom split silently mixes operating points (bench.py's
    ``bench_dd`` calls this). Same two-point-slope method; same
    "quote the slope, never the single dispatch" rule."""
    return kernel_ceiling_slope(lanes=lanes, **kw)


if __name__ == "__main__":
    r = kernel_ceiling()
    print(f"kernel: {r['lane_steps_per_sec']/1e9:.2f} G lane-steps/s, "
          f"{r['tasks_per_sec_full_occupancy']/1e6:.0f} M subintervals/s "
          f"at full occupancy ({r['wall_s']*1e3:.0f} ms, one dispatch — "
          f"RTT-polluted, see module docstring)")
    s = kernel_ceiling_slope()
    print(f"kernel SLOPE ceiling: {s['lane_steps_per_sec']/1e9:.2f} G "
          f"lane-steps/s at lanes={s['lanes']} "
          f"(outer {s['outer_lo']} vs {s['outer_hi']}; quote this one)")
    d = dd_kernel_ceiling_slope()
    print(f"dd per-chip SLOPE ceiling: {d['lane_steps_per_sec']/1e9:.2f}"
          f" G lane-steps/s at lanes={d['lanes']} (the dd leg's "
          f"headroom denominator)")
